"""veneur-emit: CLI metric/event/service-check emitter and workload
generator (reference cmd/veneur-emit/main.go). Supports statsd packet
output over udp/tcp/unix, `-command` subprocess timing, and a `-replay`
benchmark mode (the traffic generator for BASELINE configs).
"""

from __future__ import annotations

import argparse
import os
import random
import socket
import subprocess
import sys
import time


def build_metric_packet(name, value, mtype, rate=1.0, tags=()):
    parts = [f"{name}:{value}|{mtype}"]
    if rate != 1.0:
        parts.append(f"@{rate}")
    if tags:
        parts.append("#" + ",".join(tags))
    return "|".join(parts).encode()


def build_event_packet(title, text, tags=(), **fields):
    """reference cmd/veneur-emit/main.go:650 buildEventPacket. Lengths are
    BYTE lengths (the parser validates UTF-8 byte counts)."""
    body = (f"_e{{{len(title.encode())},{len(text.encode())}}}:"
            f"{title}|{text}")
    for k, v in fields.items():
        if v:
            body += f"|{k}:{v}"
    if tags:
        body += "|#" + ",".join(tags)
    return body.encode()


def build_service_check_packet(name, status, tags=(), message="",
                               timestamp="", hostname=""):
    """reference cmd/veneur-emit/main.go:715 (field order: d:, h:, then
    #tags, m: last — the parser requires the message field terminal)."""
    body = f"_sc|{name}|{status}"
    if timestamp:
        body += f"|d:{timestamp}"
    if hostname:
        body += f"|h:{hostname}"
    if tags:
        body += "|#" + ",".join(tags)
    if message:
        body += f"|m:{message}"
    return body.encode()


def open_sink(hostport: str):
    """unix:// is SOCK_STREAM on both the statsd (newline framing) and
    SSF (length framing) listeners; unixgram:// is a datagram socket.
    '@name' targets the Linux abstract namespace."""
    from veneur_tpu.server.server import resolve_addr, unix_bind_address
    kind, target = resolve_addr(hostport)
    if isinstance(target, str):
        target = unix_bind_address(target)
    if kind == "udp":
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.connect(target)
    elif kind in ("tcp", "unix"):
        sock = socket.socket(
            socket.AF_INET if kind == "tcp" else socket.AF_UNIX,
            socket.SOCK_STREAM)
        sock.connect(target)
    else:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        sock.connect(target)
    return kind, sock


def main(argv=None):
    ap = argparse.ArgumentParser(prog="veneur-emit")
    ap.add_argument("-hostport", default="udp://127.0.0.1:8126")
    ap.add_argument("-name", default="")
    ap.add_argument("-count", type=float, default=None)
    ap.add_argument("-gauge", type=float, default=None)
    ap.add_argument("-timing", default=None, help="duration like 3ms")
    ap.add_argument("-set", dest="set_", default=None)
    ap.add_argument("-tag", default="", help="comma-separated k:v tags")
    ap.add_argument("-sample_rate", type=float, default=1.0)
    ap.add_argument("-mode", default="metric",
                    choices=["metric", "event", "sc"],
                    help="payload kind (reference -mode; event/sc fields "
                         "also imply their mode)")
    ap.add_argument("-debug", action="store_true")
    # events: reference flag names are e_*; the long spellings are kept
    # as aliases
    ap.add_argument("-e_title", "-event_title", dest="event_title",
                    default="")
    ap.add_argument("-e_text", "-event_text", dest="event_text", default="")
    ap.add_argument("-e_time", default="", help="event timestamp (d:)")
    ap.add_argument("-e_hostname", default="")
    ap.add_argument("-e_aggr_key", default="")
    ap.add_argument("-e_priority", default="", help="normal|low")
    ap.add_argument("-e_source_type", default="")
    ap.add_argument("-e_alert_type", default="",
                    help="error|warning|info|success")
    ap.add_argument("-e_event_tags", default="",
                    help="comma-separated tags for the event only")
    # service checks
    ap.add_argument("-sc_name", default="")
    ap.add_argument("-sc_status", type=int, default=0)
    ap.add_argument("-sc_msg", default="")
    ap.add_argument("-sc_time", default="", help="check timestamp (d:)")
    ap.add_argument("-sc_hostname", default="")
    ap.add_argument("-sc_tags", default="",
                    help="comma-separated tags for the check only")
    # span identity (SSF mode)
    ap.add_argument("-trace_id", type=int, default=0)
    ap.add_argument("-parent_span_id", type=int, default=0)
    ap.add_argument("-span_service", default="",
                    help="alias for -service (reference flag name)")
    ap.add_argument("-span_starttime", default="")
    ap.add_argument("-span_endtime", default="")
    ap.add_argument("-error", action="store_true",
                    help="mark the emitted span as errored")
    ap.add_argument("-ssf", action="store_true",
                    help="emit SSF protobuf instead of statsd text "
                         "(reference veneur-emit -ssf)")
    ap.add_argument("-service", default="veneur-emit")
    ap.add_argument("-indicator", action="store_true")
    ap.add_argument("-command", nargs=argparse.REMAINDER, default=None,
                    help="run command, emit its wall time as a timer "
                         "(with -ssf: as a full span)")
    ap.add_argument("-replay", type=int, default=0,
                    help="benchmark mode: send N random counter packets")
    ap.add_argument("-replay_names", type=int, default=10000)
    args = ap.parse_args(argv)

    tags = [t for t in args.tag.split(",") if t]
    if args.ssf and (args.event_title or args.sc_name
                     or args.sample_rate != 1.0 or args.replay):
        print("-ssf mode does not support events, service checks, sample "
              "rates, or -replay (reference veneur-emit rejects these too)",
              file=sys.stderr)
        return 2
    # a selected mode must carry its required field — the parser on the
    # receiving end rejects nameless events/checks, so emitting one
    # would silently drop
    if ((args.mode == "event" or args.event_title or args.event_text)
            and not (args.event_title and args.event_text)):
        print("events require both -e_title and -e_text (the receiving "
              "parser rejects zero-length fields)", file=sys.stderr)
        return 2
    if args.mode == "sc" and not args.sc_name:
        print("-mode sc requires -sc_name", file=sys.stderr)
        return 2
    kind, sock = open_sink(args.hostport)
    # stream transports need the newline frame delimiter
    nl = b"\n" if kind in ("tcp", "unix") and not args.ssf else b""
    packets = []

    if args.ssf:
        return _emit_ssf(args, tags, kind, sock)

    if args.command:
        t0 = time.perf_counter()
        rc = subprocess.call(args.command)
        ms = (time.perf_counter() - t0) * 1000.0
        name = args.name or "veneur_emit.command"
        packets.append(build_metric_packet(
            name, f"{ms:.3f}", "ms", tags=tags + [f"exit_status:{rc}"]))
    elif args.replay:
        rng = random.Random(0)
        sent = 0
        t0 = time.perf_counter()
        while sent < args.replay:
            n = rng.randrange(args.replay_names)
            sock.send(build_metric_packet(
                f"replay.counter.{n}", 1, "c", tags=tags) + nl)
            sent += 1
        dt = time.perf_counter() - t0
        print(f"sent {sent} packets in {dt:.3f}s ({sent / dt:.0f}/s)")
        return 0
    else:
        if args.count is not None:
            packets.append(build_metric_packet(
                args.name, args.count, "c", args.sample_rate, tags))
        if args.gauge is not None:
            packets.append(build_metric_packet(
                args.name, args.gauge, "g", tags=tags))
        if args.timing is not None:
            from veneur_tpu.config import parse_duration
            try:
                ms = parse_duration(args.timing) * 1000.0
            except ValueError:
                print(f"-timing must be a Go duration (got "
                      f"{args.timing!r})", file=sys.stderr)
                sock.close()
                return 2
            packets.append(build_metric_packet(
                args.name, f"{ms:.3f}", "ms", args.sample_rate, tags))
        if args.set_ is not None:
            packets.append(build_metric_packet(
                args.name, args.set_, "s", tags=tags))
        if args.event_title or args.mode == "event":
            etags = tags + [t for t in args.e_event_tags.split(",") if t]
            packets.append(build_event_packet(
                args.event_title, args.event_text, etags,
                d=args.e_time, h=args.e_hostname, k=args.e_aggr_key,
                p=args.e_priority, s=args.e_source_type,
                t=args.e_alert_type))
        if args.sc_name or args.mode == "sc":
            sctags = tags + [t for t in args.sc_tags.split(",") if t]
            packets.append(build_service_check_packet(
                args.sc_name, args.sc_status, sctags, args.sc_msg,
                timestamp=args.sc_time, hostname=args.sc_hostname))

    for p in packets:
        if args.debug:
            print(f"sending {p!r}", file=sys.stderr)
        sock.send(p + nl)
    sock.close()
    return 0


def _emit_ssf(args, tags, kind, sock):
    """SSF output mode (reference cmd/veneur-emit -ssf: metrics ride a
    carrier span; -command emits a real timed span, main.go:440
    timeCommand)."""
    from veneur_tpu.proto import ssf_pb2
    from veneur_tpu.protocol.wire import write_ssf
    from veneur_tpu.samplers import ssf_samples
    from veneur_tpu.trace.tracer import Span

    # flags unset -> trace identity is inferred from the environment
    # (main.go:146,401 inferTraceIDInt): how nested `-command` spans in a
    # shell pipeline join their parent's trace. 0 means unset exactly as
    # the reference's `if existingID != 0` does (an explicit `-trace_id 0`
    # is indistinguishable there too), and the accepted integer forms
    # match Go's ParseInt — no underscores, whitespace, or leading '+'.
    # A malformed env value errors ONLY when the flag didn't decide,
    # following the module error contract: stderr + close + rc 2.
    import re

    def infer_id(existing: int, env_key: str) -> int:
        if existing:
            return existing
        raw = os.environ.get(env_key)
        if raw is None:
            return 0
        if not re.fullmatch(r"-?[0-9]+", raw):
            raise ValueError(
                f"bad integer in ${env_key}: {raw!r}")
        return int(raw)

    try:
        args.trace_id = infer_id(args.trace_id, "VENEUR_EMIT_TRACE_ID")
        args.parent_span_id = infer_id(args.parent_span_id,
                                       "VENEUR_EMIT_PARENT_SPAN_ID")
    except ValueError as e:
        print(f"veneur-emit: {e}", file=sys.stderr)
        sock.close()
        return 2

    tag_map = dict(t.split(":", 1) if ":" in t else (t, "")
                   for t in tags)
    service = args.span_service or args.service
    rc = 0
    if args.command:
        span = Span(args.name or " ".join(args.command),
                    service=service, indicator=args.indicator,
                    tags=tag_map)
        if args.trace_id:
            span.trace_id = args.trace_id
        if args.parent_span_id:
            span.parent_id = args.parent_span_id
        rc = subprocess.call(args.command)
        span.error = args.error or rc != 0
        ssf_span = span.finish()
    else:
        ssf_span = ssf_pb2.SSFSpan()
        # span descriptors apply to the carrier whether or not it has a
        # trace identity (-error/-span_service/-name must never be
        # silently dropped); -trace_id/-parent_span_id upgrade it to a
        # real trace span
        ssf_span.version = 0
        ssf_span.service = service
        ssf_span.name = args.name or "veneur-emit"
        ssf_span.indicator = args.indicator
        ssf_span.error = args.error
        ssf_span.parent_id = args.parent_span_id
        for k, v in tag_map.items():
            ssf_span.tags[k] = v
        if args.trace_id:
            from veneur_tpu.trace.tracer import _new_id
            ssf_span.trace_id = args.trace_id
            ssf_span.id = _new_id()
        now = time.time()
        from veneur_tpu.config import parse_duration
        import math

        def ts(flag, raw, default):
            """Unix seconds, or a Go duration meaning 'that long ago'.
            Raises ValueError with a usage message (caught below — the
            socket must be closed and rc returned, not SystemExit'd out
            of a programmatic main() call)."""
            if not raw:
                return int(default * 1e9)
            try:
                v = float(raw)
                if math.isfinite(v):
                    return int(v * 1e9)
            except ValueError:
                pass
            try:
                return int((now - parse_duration(raw)) * 1e9)
            except ValueError:
                raise ValueError(
                    f"{flag} must be unix seconds or a Go duration "
                    f"(got {raw!r})")
        try:
            ssf_span.start_timestamp = ts("-span_starttime",
                                          args.span_starttime, now)
            ssf_span.end_timestamp = ts("-span_endtime",
                                        args.span_endtime, now)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            sock.close()
            return 2
        samples = []
        if args.count is not None:
            samples.append(ssf_samples.count(args.name, args.count, tag_map))
        if args.gauge is not None:
            samples.append(ssf_samples.gauge(args.name, args.gauge, tag_map))
        if args.timing is not None:
            try:
                secs = parse_duration(args.timing)
            except ValueError:
                print(f"-timing must be a Go duration (got "
                      f"{args.timing!r})", file=sys.stderr)
                sock.close()
                return 2
            samples.append(ssf_samples.timing(args.name, secs, tag_map))
        if args.set_ is not None:
            samples.append(ssf_samples.set_(args.name, args.set_, tag_map))
        for s in samples:
            ssf_span.metrics.append(s)

    if args.debug:
        print(f"sending span {ssf_span!r}".replace("\n", " "),
              file=sys.stderr)
    if kind in ("tcp", "unix"):
        f = sock.makefile("wb")
        write_ssf(f, ssf_span)
        f.flush()
    else:
        sock.send(ssf_span.SerializeToString())
    sock.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
