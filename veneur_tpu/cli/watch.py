"""veneur-tpu-watch: operator tool for the streaming watch tier
(README §Watches).

Registers, lists, deletes and tails standing monitors on a running
server (which must run with watch_enabled: true):

  python -m veneur_tpu.cli.watch register page.latency \\
      --kind quantile -q 0.99 --op '>' --threshold 250 \\
      --hysteresis 25 --for-intervals 3
  python -m veneur_tpu.cli.watch register --prefix api. \\
      --threshold 1000 --json
  python -m veneur_tpu.cli.watch list
  python -m veneur_tpu.cli.watch delete 7
  python -m veneur_tpu.cli.watch tail --json

`tail` follows GET /watch/stream (SSE) and prints one line per state
transition until interrupted; `--json` emits raw event bodies.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import urllib.error
import urllib.request

log = logging.getLogger("veneur_tpu.cli.watch")

DEFAULT_URL = "http://127.0.0.1:8127"


def build_registration(args) -> dict:
    body: dict = {"kind": args.kind}
    if args.prefix is not None:
        body["prefix"] = args.prefix
    elif args.match is not None:
        body["match"] = args.match
    elif args.name is not None:
        body["name"] = args.name
    else:
        raise SystemExit("need a metric name, --prefix, or --match")
    body["op"] = args.op
    if args.threshold is None:
        raise SystemExit("--threshold is required")
    body["threshold"] = args.threshold
    if args.hysteresis:
        body["hysteresis"] = args.hysteresis
    if args.for_intervals != 1:
        body["for_intervals"] = args.for_intervals
    if args.no_data_intervals:
        body["no_data_intervals"] = args.no_data_intervals
    if args.kind == "quantile" and args.quantile is not None:
        body["quantile"] = args.quantile
    if args.metric_kind:
        body["metric_kinds"] = args.metric_kind
    if args.tag:
        body["tags"] = args.tag
    if args.description:
        body["description"] = args.description
    return body


def _request(url: str, timeout: float, method: str = "GET",
             body: dict | None = None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def _watch_line(w: dict) -> str:
    sel = next((f"{m}={w[m]}" for m in ("name", "prefix", "match")
                if m in w), "?")
    parts = [f"#{w['id']}", w.get("status", "?"), w["kind"], sel,
             f"{w['op']} {w['threshold']:g}"]
    if w.get("hysteresis"):
        parts.append(f"hyst={w['hysteresis']:g}")
    if w.get("for_intervals", 1) != 1:
        parts.append(f"for={w['for_intervals']}")
    if "value" in w:
        parts.append(f"value={w['value']:g}")
    return "  ".join(parts)


def _event_line(ev: dict) -> str:
    sel = next((ev[m] for m in ("name", "prefix", "match") if m in ev),
               "?")
    line = (f"watch #{ev['id']} [{ev['kind']}] {sel}: "
            f"{ev['from']} -> {ev['to']} @ {ev['ts']}")
    if "value" in ev:
        line += f" (value={ev['value']:g}, threshold={ev['threshold']:g})"
    if ev.get("stale_bounded"):
        line += " [stale-bounded]"
    return line


def cmd_register(args) -> int:
    with _request(f"{args.url}/watch", args.timeout, "POST",
                  build_registration(args)) as resp:
        out = json.loads(resp.read())
    if args.as_json:
        print(json.dumps(out, indent=1))
    else:
        print(f"registered watch #{out['id']}")
    return 0


def cmd_list(args) -> int:
    with _request(f"{args.url}/watch", args.timeout) as resp:
        out = json.loads(resp.read())
    if args.as_json:
        print(json.dumps(out, indent=1))
        return 0
    for w in out.get("watches", []):
        print(_watch_line(w))
    if not out.get("watches"):
        print("(no watches registered)")
    return 0


def cmd_delete(args) -> int:
    with _request(f"{args.url}/watch/{args.id}", args.timeout,
                  "DELETE") as resp:
        out = json.loads(resp.read())
    if args.as_json:
        print(json.dumps(out, indent=1))
    else:
        print(f"deleted watch #{out['deleted']}")
    return 0


def tail_events(resp, limit: int | None = None):
    """Yield parsed event dicts from an open SSE response; SSE comment
    lines (keepalives) are skipped. Stops after `limit` events (tests)
    or when the server closes the stream."""
    n = 0
    for raw in resp:
        line = raw.strip()
        if not line.startswith(b"data: "):
            continue   # comment/keepalive or blank separator
        yield json.loads(line[len(b"data: "):])
        n += 1
        if limit is not None and n >= limit:
            return


def cmd_tail(args) -> int:
    # no read timeout on purpose: keepalive comments arrive every
    # second, so a dead server surfaces quickly anyway
    resp = _request(f"{args.url}/watch/stream", args.timeout)
    with resp:
        try:
            for ev in tail_events(resp, limit=args.limit or None):
                if args.as_json:
                    print(json.dumps(ev))
                else:
                    print(_event_line(ev))
                sys.stdout.flush()
        except KeyboardInterrupt:
            pass
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="veneur-tpu-watch")
    ap.add_argument("--url", default=DEFAULT_URL,
                    help=f"server base URL (default {DEFAULT_URL})")
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print raw response bodies")
    sub = ap.add_subparsers(dest="command", required=True)

    reg = sub.add_parser("register", help="register one watch")
    reg.add_argument("name", nargs="?", default=None,
                     help="exact metric name (all tag variants)")
    reg.add_argument("--prefix", default=None,
                     help="every metric whose name starts with this")
    reg.add_argument("--match", default=None,
                     help="fnmatch-style wildcard pattern")
    reg.add_argument("--kind", default="threshold",
                     choices=["threshold", "delta", "quantile",
                              "cardinality"])
    reg.add_argument("--op", default=">",
                     choices=[">", ">=", "<", "<="])
    reg.add_argument("--threshold", type=float, default=None)
    reg.add_argument("--hysteresis", type=float, default=0.0)
    reg.add_argument("--for-intervals", type=int, default=1,
                     dest="for_intervals")
    reg.add_argument("--no-data-intervals", type=int, default=0,
                     dest="no_data_intervals")
    reg.add_argument("-q", "--quantile", type=float, default=None,
                     metavar="P", help="quantile for --kind quantile")
    reg.add_argument("--metric-kind", action="append", default=[],
                     dest="metric_kind",
                     help="restrict the selector's metric kinds")
    reg.add_argument("--tag", action="append", default=[],
                     metavar="K:V", help="exact tag-set filter")
    reg.add_argument("--description", default="")
    reg.set_defaults(fn=cmd_register)

    lst = sub.add_parser("list", help="list registered watches")
    lst.set_defaults(fn=cmd_list)

    dele = sub.add_parser("delete", help="delete one watch by id")
    dele.add_argument("id", type=int)
    dele.set_defaults(fn=cmd_delete)

    tail = sub.add_parser("tail", help="follow /watch/stream")
    tail.add_argument("--limit", type=int, default=0,
                      help="stop after N events (0 = forever)")
    tail.set_defaults(fn=cmd_tail)

    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    try:
        return args.fn(args)
    except urllib.error.HTTPError as e:
        print(f"watch {args.command} failed: HTTP {e.code}: "
              f"{e.read().decode(errors='replace')}", file=sys.stderr)
        return 1
    except Exception as e:
        print(f"watch {args.command} failed: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
