"""veneur-tpu-telemetry: one-shot operator view of a running server's
telemetry registry (README §Observability).

Scrapes GET /metrics once (the server must run with
prometheus_metrics_enabled: true) and prints every series as one
sorted `name{labels} value` line — grep-friendly, diff-friendly, no
Prometheus required. `--json` emits the same series as a list of
{name, labels, value, type} objects.

  python -m veneur_tpu.cli.telemetry http://127.0.0.1:8127/metrics
  python -m veneur_tpu.cli.telemetry --json | jq '.[].name'
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from veneur_tpu.cli.prometheus import make_fetcher, parse_exposition

log = logging.getLogger("veneur_tpu.cli.telemetry")

DEFAULT_URL = "http://127.0.0.1:8127/metrics"


def _format_series(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def ring_table(samples) -> list:
    """Render the multi-ring ingest family (veneur.ring.per_ring_*,
    ring=<i> label) as one aligned row per ring — the operator's at-a-
    glance skew check (one cold ring = a mis-pinned core or a kernel
    flow-hash imbalance). Empty outside multi-ring mode."""
    per_ring: dict = {}
    cols: list = []
    for name, labels, value in samples:
        if "per_ring_" not in name or "ring" not in labels:
            continue
        stat = name.split("per_ring_", 1)[1]
        if stat not in cols:
            cols.append(stat)
        per_ring.setdefault(labels["ring"], {})[stat] = value
    if not per_ring:
        return []
    rows = [["ring"] + cols]
    for ring in sorted(per_ring, key=lambda r: (len(r), r)):
        rows.append([ring] + [f"{per_ring[ring].get(c, 0):g}"
                              for c in cols])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    return ["  ".join(f"{cell:>{w}}" for cell, w in zip(r, widths))
            for r in rows]


def watch_table(samples) -> list:
    """Render the streaming watch tier family (veneur.watch.*,
    kind=<watch kind> label) as one aligned row per watch kind — the
    operator's firing/suppression/drop balance sheet (README §Watches).
    Empty when the watch tier is off or has no registrations."""
    per_kind: dict = {}
    cols: list = []
    for name, labels, value in samples:
        # exposition names arrive underscore-mangled (veneur_watch_*)
        if not name.startswith("veneur_watch_") or "kind" not in labels:
            continue
        stat = name[len("veneur_watch_"):]
        if stat.endswith("_total"):
            stat = stat[:-len("_total")]
        if stat not in cols:
            cols.append(stat)
        per_kind.setdefault(labels["kind"], {})[stat] = value
    if not per_kind:
        return []
    rows = [["kind"] + cols]
    for kind in sorted(per_kind):
        rows.append([kind] + [f"{per_kind[kind].get(c, 0):g}"
                              for c in cols])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    return ["  ".join(f"{cell:>{w}}" for cell, w in zip(r, widths))
            for r in rows]


def tenant_table(samples) -> list:
    """Render the multi-tenant fairness family (veneur.tenant.*,
    tenant=<name> label) as one aligned row per tenant — the operator's
    noisy-neighbor balance sheet: admitted vs shed per tenant, plus the
    quarantine flag and demoted-row total (README §Multi-tenancy).
    Empty when tenancy is off."""
    per_tenant: dict = {}
    cols: list = []
    for name, labels, value in samples:
        # exposition names arrive underscore-mangled (veneur_tenant_*)
        if not name.startswith("veneur_tenant_") or "tenant" not in labels:
            continue
        stat = name[len("veneur_tenant_"):]
        if stat.endswith("_total"):
            stat = stat[:-len("_total")]
        if stat not in cols:
            cols.append(stat)
        per_tenant.setdefault(labels["tenant"], {})[stat] = value
    if not per_tenant:
        return []
    rows = [["tenant"] + cols]
    for tenant in sorted(per_tenant):
        rows.append([tenant] + [f"{per_tenant[tenant].get(c, 0):g}"
                                for c in cols])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    return ["  ".join(f"{cell:>{w}}" for cell, w in zip(r, widths))
            for r in rows]


def keytable_table(samples) -> list:
    """Render the self-adjusting key-table family (veneur.table.*,
    kind=<table kind> label) as one aligned row per kind — the
    operator's capacity/pressure balance sheet: current capacity, grow
    count, and the exact evicted/merged/demoted accounting that proves
    no row was lost silently (README §Key tables). Empty when growth
    is off."""
    per_kind: dict = {}
    cols: list = []
    for name, labels, value in samples:
        # exposition names arrive underscore-mangled (veneur_table_*)
        if not name.startswith("veneur_table_") or "kind" not in labels:
            continue
        stat = name[len("veneur_table_"):]
        if stat.endswith("_total"):
            stat = stat[:-len("_total")]
        if stat not in cols:
            cols.append(stat)
        per_kind.setdefault(labels["kind"], {})[stat] = value
    if not per_kind:
        return []
    rows = [["kind"] + cols]
    for kind in sorted(per_kind):
        rows.append([kind] + [f"{per_kind[kind].get(c, 0):g}"
                              for c in cols])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    return ["  ".join(f"{cell:>{w}}" for cell, w in zip(r, widths))
            for r in rows]


def dump_once(fetch, as_json: bool, out=None) -> int:
    """One scrape → sorted text (or JSON) on `out`. Returns an exit
    code: 1 on fetch failure, 0 otherwise (an empty exposition is a
    valid — if suspicious — answer, reported as such)."""
    out = out if out is not None else sys.stdout
    try:
        text = fetch()
    except Exception as e:
        print(f"scrape failed: {e}", file=sys.stderr)
        return 1
    types, samples = parse_exposition(text)
    rows = sorted((_format_series(n, lb), v, types.get(n, ""))
                  for n, lb, v in samples)
    if as_json:
        print(json.dumps([{"series": s, "value": v, "type": t}
                          for s, v, t in rows], indent=1), file=out)
        return 0
    if not rows:
        print("(empty exposition — is prometheus_metrics_enabled on?)",
              file=out)
        return 0
    width = max(len(s) for s, _, _ in rows)
    for series, value, _ in rows:
        print(f"{series:<{width}}  {value:g}", file=out)
    table = ring_table(samples)
    if table:
        print("", file=out)
        print("native ingest rings:", file=out)
        for line in table:
            print(f"  {line}", file=out)
    table = watch_table(samples)
    if table:
        print("", file=out)
        print("standing watches:", file=out)
        for line in table:
            print(f"  {line}", file=out)
    table = tenant_table(samples)
    if table:
        print("", file=out)
        print("tenants:", file=out)
        for line in table:
            print(f"  {line}", file=out)
    table = keytable_table(samples)
    if table:
        print("", file=out)
        print("key tables:", file=out)
        for line in table:
            print(f"  {line}", file=out)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="veneur-tpu-telemetry")
    ap.add_argument("url", nargs="?", default=DEFAULT_URL,
                    help=f"the server's /metrics URL "
                         f"(default {DEFAULT_URL})")
    ap.add_argument("--socket", default=None,
                    help="scrape over a unix socket instead of TCP")
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    fetch = make_fetcher(args.url, socket_path=args.socket,
                         timeout=args.timeout)
    return dump_once(fetch, args.as_json)


if __name__ == "__main__":
    sys.exit(main())
