"""veneur-tpu-query: one-shot client for the on-device query tier
(README §Query tier).

POSTs one query to a running server's /query endpoint (the server
must run with query_enabled: true) and prints each match as a
grep-friendly line; `--json` emits the raw response body.

  python -m veneur_tpu.cli.query page.latency -q 0.5 -q 0.99
  python -m veneur_tpu.cli.query --prefix api. --kind counter
  python -m veneur_tpu.cli.query --match 'api.*.errors' --json

Range queries (server must also run with history_enabled: true) read
the on-device history ring instead of the live interval — one point
per step, oldest first (README §History):

  python -m veneur_tpu.cli.query api.hits --range 15m --step 1m
  python -m veneur_tpu.cli.query page.latency --range 1h \\
      --window 5m --step 1m -q 0.99 --json

--range/--window/--step accept seconds or 30s/15m/2h/1d suffixes.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import urllib.error
import urllib.request

log = logging.getLogger("veneur_tpu.cli.query")

DEFAULT_URL = "http://127.0.0.1:8127/query"

_DUR_SUFFIX = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_duration(text: str) -> float:
    """'90', '90s', '15m', '2h', '1d' -> seconds (float, > 0)."""
    text = str(text).strip()
    mult = 1.0
    if text and text[-1].lower() in _DUR_SUFFIX:
        mult = _DUR_SUFFIX[text[-1].lower()]
        text = text[:-1]
    try:
        v = float(text) * mult
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"bad duration {text!r} (use seconds or 30s/15m/2h/1d)")
    if not v > 0:
        raise argparse.ArgumentTypeError("duration must be positive")
    return v


def build_query(args) -> dict:
    q: dict = {}
    if args.prefix is not None:
        q["prefix"] = args.prefix
    elif args.match is not None:
        q["match"] = args.match
    elif args.name is not None:
        q["name"] = args.name
    else:
        raise SystemExit("need a metric name, --prefix, or --match")
    if args.kind:
        q["kinds"] = args.kind
    if args.quantile:
        q["quantiles"] = args.quantile
    if args.tag:
        q["tags"] = args.tag
    if getattr(args, "range", None) is not None:
        q["range"] = args.range
        if args.window is not None:
            q["window"] = args.window
        if args.step is not None:
            q["step"] = args.step
    elif args.window is not None or args.step is not None:
        raise SystemExit("--window/--step only apply with --range")
    return q


def post_query(url: str, body: dict, timeout: float) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _fields(m: dict) -> str:
    """Everything after name/kind/tags, stable order, `k=v` pairs;
    quantiles inline as q<p>=v."""
    parts = []
    for k in ("value", "rate", "delta", "estimate", "message", "count",
              "sum", "avg", "hmean", "median", "min", "max"):
        if k in m and m[k] is not None:
            v = m[k]
            parts.append(f"{k}={v:g}" if isinstance(v, float) else
                         f"{k}={v}")
    for p, v in sorted(m.get("quantiles", {}).items(),
                       key=lambda kv: float(kv[0])):
        if v is not None:
            parts.append(f"q{p}={v:g}")
    return "  ".join(parts)


def _render_points(m: dict, dest) -> None:
    """One line per range point, oldest first: timestamp, seq span, the
    point's fields, and (incomplete) when part of the span fell off
    retention."""
    for p in m.get("points", []):
        span = p.get("seq") or ["?", "?"]
        mark = "" if p.get("complete") else "  (incomplete)"
        print(f"  {p.get('ts', 0):.0f}  seq[{span[0]}..{span[1]}]  "
              f"{_fields(p)}{mark}", file=dest)


def render(out: dict, dest=None) -> None:
    dest = dest if dest is not None else sys.stdout
    for res in out.get("results", []):
        for m in res.get("matches", []):
            tags = ",".join(m.get("tags", []))
            series = m["name"] + (f"{{{tags}}}" if tags else "")
            if res.get("range"):
                print(f"{series}  [{m['kind']}]", file=dest)
                _render_points(m, dest)
            else:
                print(f"{series}  [{m['kind']}]  {_fields(m)}", file=dest)
        if res.get("truncated"):
            print("(match list truncated)", file=dest)
    if not any(r.get("matches") for r in out.get("results", [])):
        print("(no matches)", file=dest)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="veneur-tpu-query")
    ap.add_argument("name", nargs="?", default=None,
                    help="exact metric name (all tag variants)")
    ap.add_argument("--prefix", default=None,
                    help="every metric whose name starts with this")
    ap.add_argument("--match", default=None,
                    help="fnmatch-style wildcard pattern")
    ap.add_argument("--kind", action="append", default=[],
                    choices=["counter", "gauge", "status", "set",
                             "histogram", "timer"],
                    help="restrict to kind(s); repeatable")
    ap.add_argument("-q", "--quantile", action="append", type=float,
                    default=[], metavar="P",
                    help="quantile in [0,1] for histos/timers; repeatable")
    ap.add_argument("--tag", action="append", default=[], metavar="K:V",
                    help="exact tag-set filter; repeat for each tag")
    ap.add_argument("--range", type=parse_duration, default=None,
                    metavar="DUR",
                    help="history lookback (e.g. 900, 15m, 1h) — answers "
                         "from the on-device history ring")
    ap.add_argument("--window", type=parse_duration, default=None,
                    metavar="DUR",
                    help="sliding aggregation window per point "
                         "(default: one step)")
    ap.add_argument("--step", type=parse_duration, default=None,
                    metavar="DUR",
                    help="stride between points (default: the whole range "
                         "as one point)")
    ap.add_argument("--url", default=DEFAULT_URL,
                    help=f"the server's /query URL (default {DEFAULT_URL})")
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the raw response body")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    body = {"queries": [build_query(args)]}
    try:
        out = post_query(args.url, body, args.timeout)
    except urllib.error.HTTPError as e:
        print(f"query failed: HTTP {e.code}: "
              f"{e.read().decode(errors='replace')}", file=sys.stderr)
        return 1
    except Exception as e:
        print(f"query failed: {e}", file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(out, indent=1))
    else:
        render(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
