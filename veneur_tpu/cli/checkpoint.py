"""veneur-tpu-checkpoint: operator tooling for the durability layer
(veneur_tpu/persistence/; README §Durability).

  inspect <path>   print what a checkpoint (or every checkpoint under a
                   checkpoint_dir root) claims to hold: manifest fields,
                   per-kind row counts, spill entries, byte sizes, age —
                   WITHOUT validating chunk bytes
  verify <path>    full validation: manifest structure, format version,
                   schema hash, every chunk CRC. Exit 0 only when every
                   checkpoint examined is loadable.

`<path>` may be one ckpt-NNNNNNNN directory or a checkpoint_dir root;
roots examine every complete checkpoint, oldest first. Quarantined
snapshots (root/quarantine/) are never examined — they already failed.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

from veneur_tpu.persistence.codec import (CorruptSnapshot, MANIFEST_NAME,
                                          list_checkpoints, read_manifest,
                                          verify_dir)

log = logging.getLogger("veneur_tpu.cli.checkpoint")


def _targets(path: str):
    """-> [(label, dirpath)] — the one directory if it is itself a
    checkpoint, else every complete checkpoint under it."""
    if os.path.isfile(os.path.join(path, MANIFEST_NAME)):
        return [(os.path.basename(path.rstrip("/")), path)]
    return [(f"ckpt-{seq:08d}", p) for seq, p in list_checkpoints(path)]


def _describe(manifest: dict, dirpath: str) -> dict:
    try:
        disk_bytes = sum(
            os.path.getsize(os.path.join(dirpath, f))
            for f in os.listdir(dirpath)
            if os.path.isfile(os.path.join(dirpath, f)))
    except OSError:
        disk_bytes = None
    return {
        "path": dirpath,
        "format_version": manifest.get("format_version"),
        "agg_kind": manifest.get("agg_kind"),
        "n_shards": manifest.get("n_shards"),
        "hostname": manifest.get("hostname", ""),
        "interval_ts": manifest.get("interval_ts"),
        "created_at": manifest.get("created_at"),
        "age_s": round(time.time() - float(manifest.get("created_at", 0)),
                       1),
        "rows": manifest.get("rows", {}),
        "live_keys": sum((manifest.get("rows") or {}).values()),
        "spill_entries": manifest.get("spill_entries", 0),
        "chunk_bytes": manifest.get("total_bytes"),
        "disk_bytes": disk_bytes,
    }


def cmd_inspect(path: str, as_json: bool) -> int:
    targets = _targets(path)
    if not targets:
        print(f"no checkpoints under {path}", file=sys.stderr)
        return 1
    out = []
    rc = 0
    for label, dirpath in targets:
        try:
            out.append(_describe(read_manifest(dirpath), dirpath))
        except CorruptSnapshot as e:
            rc = 1
            out.append({"path": dirpath, "error": str(e)})
    if as_json:
        print(json.dumps(out, indent=1))
        return rc
    for d in out:
        if "error" in d:
            print(f"{d['path']}: CORRUPT: {d['error']}")
            continue
        print(f"{d['path']}: {d['agg_kind']} x{d['n_shards']} "
              f"host={d['hostname'] or '-'} "
              f"interval_ts={d['interval_ts']} age={d['age_s']}s")
        rows = " ".join(f"{k}={v}" for k, v in sorted(d["rows"].items()))
        print(f"  rows: {rows} (total {d['live_keys']}) "
              f"spill_entries={d['spill_entries']}")
        print(f"  bytes: chunks={d['chunk_bytes']} disk={d['disk_bytes']}")
    return rc


def cmd_verify(path: str, as_json: bool) -> int:
    targets = _targets(path)
    if not targets:
        print(f"no checkpoints under {path}", file=sys.stderr)
        return 1
    results = []
    rc = 0
    for label, dirpath in targets:
        try:
            verify_dir(dirpath)
            results.append({"path": dirpath, "ok": True})
        except CorruptSnapshot as e:
            rc = 1
            results.append({"path": dirpath, "ok": False,
                            "error": str(e)})
    if as_json:
        print(json.dumps(results, indent=1))
        return rc
    for r in results:
        if r["ok"]:
            print(f"{r['path']}: OK")
        else:
            print(f"{r['path']}: CORRUPT: {r['error']}")
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(prog="veneur-tpu-checkpoint")
    sub = ap.add_subparsers(dest="command", required=True)
    for name in ("inspect", "verify"):
        sp = sub.add_parser(name)
        sp.add_argument("path",
                        help="one checkpoint directory or a "
                             "checkpoint_dir root")
        sp.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    if args.command == "inspect":
        return cmd_inspect(args.path, args.as_json)
    return cmd_verify(args.path, args.as_json)


if __name__ == "__main__":
    sys.exit(main())
