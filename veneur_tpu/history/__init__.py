"""On-device history tier: a packed per-key ring of the last K flush
intervals in HBM, with tiered 2x decimation and windowed-merge range
queries (ROADMAP item 4; ISSUE 18).

    spec.py     HistorySpec — frozen shape contract (ring geometry)
    device.py   HistoryState + jitted write / decimate / read programs
    writer.py   HistoryWriter — host admission index, window metadata,
                fused-flush protocol, persistence
    merge.py    range-merge programs (XLA chain + combined launch) and
                the packed wire helpers

The Pallas variant of the masked HLL window merge lives in
ops/pallas_history.py behind the same probe gating as the digest
kernel.
"""

from veneur_tpu.history.spec import HistorySpec
from veneur_tpu.history.writer import HistoryPlan, HistoryWriter, RangePlan

__all__ = ["HistorySpec", "HistoryWriter", "HistoryPlan", "RangePlan"]
