"""Device-resident history ring: state container + the jitted write,
decimation-merge and column-read programs.

Everything here follows the donation discipline of the ingest step: the
ring is threaded through `write_window` / `roll_tiers` / `wipe_rows` as
a donated argument, so the steady state holds exactly ONE HistoryState
in HBM (the analytic budget in HistorySpec.hbm_bytes is also the real
one). Callers (history/writer.py) serialize every dispatch that touches
the ring under one lock and swap their reference to the returned state;
readers grab the current reference under the same lock before
dispatching, which is safe against donation because an enqueued
execution keeps its input buffers alive until it retires.

Absence is encoded in the values, not in side masks — each kind's
neutral element is also its merge identity, so decimation and range
merges need no occupancy bookkeeping:

    counter   (0, 0)        additive identity of the two-float pair
    gauge     NaN           LWW skips NaN (newer finite value wins)
    status    NaN           same
    hll       all-zero      register max identity
    digest    weight 0      compress_rows ignores empty cells
    min/max   +inf / -inf   order identities
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from veneur_tpu.history.spec import HistorySpec
from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.ops import tdigest as td
from veneur_tpu.utils.numerics import twofloat_merge


class HistoryState(NamedTuple):
    """One ring per kind; axis 1 is the flat column index
    tier * windows + (slot % windows) (see spec.py)."""
    counter_hi: jax.Array   # f32[Rc, W]
    counter_lo: jax.Array   # f32[Rc, W]
    gauge: jax.Array        # f32[Rg, W]
    status: jax.Array       # f32[Rst, W]
    hll: jax.Array          # i32[Rs, W, hll_words]
    h_mean: jax.Array       # f32[Rh, W, C]
    h_weight: jax.Array     # f32[Rh, W, C]
    h_min: jax.Array        # f32[Rh, W]
    h_max: jax.Array        # f32[Rh, W]
    h_count_hi: jax.Array   # f32[Rh, W]
    h_count_lo: jax.Array   # f32[Rh, W]
    h_sum_hi: jax.Array     # f32[Rh, W]
    h_sum_lo: jax.Array     # f32[Rh, W]


HISTORY_FIELDS = HistoryState._fields

# write_window's value-dict contract (all in table get_meta order,
# padded to the dest buckets): h_mean/h_weight arrive at the FLUSH
# table's cell count and are compressed to hspec.centroids in-program.
WRITE_KEYS = ("counter_hi", "counter_lo", "gauge", "status", "hll",
              "h_mean", "h_weight", "h_min", "h_max",
              "h_count_hi", "h_count_lo", "h_sum_hi", "h_sum_lo")


def empty_history(hspec: HistorySpec) -> HistoryState:
    w = hspec.total_cols
    f32 = jnp.float32
    return HistoryState(
        counter_hi=jnp.zeros((hspec.counter_rows, w), f32),
        counter_lo=jnp.zeros((hspec.counter_rows, w), f32),
        gauge=jnp.full((hspec.gauge_rows, w), jnp.nan, f32),
        status=jnp.full((hspec.status_rows, w), jnp.nan, f32),
        hll=jnp.zeros((hspec.set_rows, w, hspec.hll_words), jnp.int32),
        h_mean=jnp.zeros((hspec.histo_rows, w, hspec.centroids), f32),
        h_weight=jnp.zeros((hspec.histo_rows, w, hspec.centroids), f32),
        h_min=jnp.full((hspec.histo_rows, w), jnp.inf, f32),
        h_max=jnp.full((hspec.histo_rows, w), -jnp.inf, f32),
        h_count_hi=jnp.zeros((hspec.histo_rows, w), f32),
        h_count_lo=jnp.zeros((hspec.histo_rows, w), f32),
        h_sum_hi=jnp.zeros((hspec.histo_rows, w), f32),
        h_sum_lo=jnp.zeros((hspec.histo_rows, w), f32),
    )


_NEUTRAL = {
    "counter_hi": 0.0, "counter_lo": 0.0,
    "gauge": jnp.nan, "status": jnp.nan,
    "h_min": jnp.inf, "h_max": -jnp.inf,
    "h_count_hi": 0.0, "h_count_lo": 0.0,
    "h_sum_hi": 0.0, "h_sum_lo": 0.0,
}


def _clear_column(hist: HistoryState, col) -> HistoryState:
    """Neutralize ring column `col` for every kind — the ring-wraparound
    eviction of the window being overwritten."""
    out = {}
    for name in HISTORY_FIELDS:
        a = getattr(hist, name)
        if a.ndim == 2:
            out[name] = a.at[:, col].set(jnp.float32(_NEUTRAL[name]))
        else:
            out[name] = a.at[:, col, :].set(
                jnp.zeros((a.shape[0], a.shape[2]), a.dtype))
    return HistoryState(**out)


def write_window_core(hist: HistoryState, vals: dict, dests: tuple, col,
                      *, hspec: HistorySpec, clear: bool):
    """Scatter one flush interval's per-key values into ring column
    `col`. `dests` is (counter, gauge, status, set, histo) i32 row
    arrays in get_meta order, padded with an out-of-range sentinel
    (>= rows) so pads drop; `clear` neutralizes the column first (set
    by the FIRST block of a tiled flush only). This function is inlined
    into the flush program itself (aggregation/step.py
    flush_live_hist_packed) — the "one extra fused write" — and is also
    its own jit (`write_window`) for host-fed backends and the replay
    oracle, so both paths store bit-identical bytes by construction."""
    if clear:
        hist = _clear_column(hist, col)
    dc, dg, dst_, ds, dh = dests

    def put(arr, dest, v):
        return arr.at[dest, col].set(v, mode="drop")

    cm, cw = td.compress_rows(
        vals["h_mean"], vals["h_weight"], compression=hspec.compression,
        cells_per_k=hspec.cells_per_k, out_c=hspec.centroids,
        exact_extremes=hspec.exact_extremes)
    return HistoryState(
        counter_hi=put(hist.counter_hi, dc, vals["counter_hi"]),
        counter_lo=put(hist.counter_lo, dc, vals["counter_lo"]),
        gauge=put(hist.gauge, dg, vals["gauge"]),
        status=put(hist.status, dst_, vals["status"]),
        hll=hist.hll.at[ds, col, :].set(vals["hll"], mode="drop"),
        h_mean=hist.h_mean.at[dh, col, :].set(cm, mode="drop"),
        h_weight=hist.h_weight.at[dh, col, :].set(cw, mode="drop"),
        h_min=put(hist.h_min, dh, vals["h_min"]),
        h_max=put(hist.h_max, dh, vals["h_max"]),
        h_count_hi=put(hist.h_count_hi, dh, vals["h_count_hi"]),
        h_count_lo=put(hist.h_count_lo, dh, vals["h_count_lo"]),
        h_sum_hi=put(hist.h_sum_hi, dh, vals["h_sum_hi"]),
        h_sum_lo=put(hist.h_sum_lo, dh, vals["h_sum_lo"]),
    )


write_window = partial(
    jax.jit, static_argnames=("hspec", "clear"),
    donate_argnames=("hist",))(write_window_core)


def wipe_rows_core(hist: HistoryState, resets: tuple, *,
                   hspec: HistorySpec):
    """Neutralize whole ROWS across every column — run when the writer
    reassigns an evicted key's row to a new key, so the new key never
    inherits the old key's windows. `resets` mirrors `dests` (i32 per
    kind, sentinel-padded)."""
    dc, dg, dst_, ds, dh = resets
    w = hspec.total_cols

    def wipe(arr, rows, fill):
        v = jnp.full((rows.shape[0], w), jnp.float32(fill))
        return arr.at[rows, :].set(v, mode="drop")

    def wipe3(arr, rows):
        v = jnp.zeros((rows.shape[0], w, arr.shape[2]), arr.dtype)
        return arr.at[rows, :, :].set(v, mode="drop")

    return HistoryState(
        counter_hi=wipe(hist.counter_hi, dc, 0.0),
        counter_lo=wipe(hist.counter_lo, dc, 0.0),
        gauge=wipe(hist.gauge, dg, jnp.nan),
        status=wipe(hist.status, dst_, jnp.nan),
        hll=wipe3(hist.hll, ds),
        h_mean=wipe3(hist.h_mean, dh),
        h_weight=wipe3(hist.h_weight, dh),
        h_min=wipe(hist.h_min, dh, jnp.inf),
        h_max=wipe(hist.h_max, dh, -jnp.inf),
        h_count_hi=wipe(hist.h_count_hi, dh, 0.0),
        h_count_lo=wipe(hist.h_count_lo, dh, 0.0),
        h_sum_hi=wipe(hist.h_sum_hi, dh, 0.0),
        h_sum_lo=wipe(hist.h_sum_lo, dh, 0.0),
    )


wipe_rows = partial(
    jax.jit, static_argnames=("hspec",),
    donate_argnames=("hist",))(wipe_rows_core)


def roll_tiers_core(hist: HistoryState, src0, src1, dst, *,
                    hspec: HistorySpec):
    """Decimation merge: fold columns src0 (older) and src1 (newer) of
    tier t-1 into column dst of tier t, for ALL rows at once. Column
    indices are TRACED scalars so one compiled executable serves every
    (tier, slot) combination — amortized launch cost per flush is
    sum(2^-t) < 1.

    Merge semantics per kind: counters and histo count/sum fold with
    compensated two-float merges; gauges/status are last-writer-wins
    (src1 wins when finite); HLL takes the register max (exact union);
    digest centroids concatenate and re-compress through the SAME
    k-cell compression as the window write, which is what keeps
    decimated quantiles inside the t-digest merge bound."""
    def colv(arr, c):
        return jax.lax.dynamic_index_in_dim(arr, c, axis=1,
                                            keepdims=False)

    chi, clo = twofloat_merge(
        colv(hist.counter_hi, src0), colv(hist.counter_lo, src0),
        colv(hist.counter_hi, src1), colv(hist.counter_lo, src1))
    g0, g1 = colv(hist.gauge, src0), colv(hist.gauge, src1)
    gauge = jnp.where(jnp.isnan(g1), g0, g1)
    s0, s1 = colv(hist.status, src0), colv(hist.status, src1)
    status = jnp.where(jnp.isnan(s1), s0, s1)
    p = hspec.hll_precision
    regs = jnp.maximum(
        hll_ops.unpack_registers(colv(hist.hll, src0), precision=p),
        hll_ops.unpack_registers(colv(hist.hll, src1), precision=p))
    words = hll_ops.pack_registers(regs, precision=p)
    mcat = jnp.concatenate(
        [colv(hist.h_mean, src0), colv(hist.h_mean, src1)], axis=-1)
    wcat = jnp.concatenate(
        [colv(hist.h_weight, src0), colv(hist.h_weight, src1)], axis=-1)
    cm, cw = td.compress_rows(
        mcat, wcat, compression=hspec.compression,
        cells_per_k=hspec.cells_per_k, out_c=hspec.centroids,
        exact_extremes=hspec.exact_extremes)
    hct_hi, hct_lo = twofloat_merge(
        colv(hist.h_count_hi, src0), colv(hist.h_count_lo, src0),
        colv(hist.h_count_hi, src1), colv(hist.h_count_lo, src1))
    hs_hi, hs_lo = twofloat_merge(
        colv(hist.h_sum_hi, src0), colv(hist.h_sum_lo, src0),
        colv(hist.h_sum_hi, src1), colv(hist.h_sum_lo, src1))
    return HistoryState(
        counter_hi=hist.counter_hi.at[:, dst].set(chi),
        counter_lo=hist.counter_lo.at[:, dst].set(clo),
        gauge=hist.gauge.at[:, dst].set(gauge),
        status=hist.status.at[:, dst].set(status),
        hll=hist.hll.at[:, dst, :].set(words),
        h_mean=hist.h_mean.at[:, dst, :].set(cm),
        h_weight=hist.h_weight.at[:, dst, :].set(cw),
        h_min=hist.h_min.at[:, dst].set(
            jnp.minimum(colv(hist.h_min, src0), colv(hist.h_min, src1))),
        h_max=hist.h_max.at[:, dst].set(
            jnp.maximum(colv(hist.h_max, src0), colv(hist.h_max, src1))),
        h_count_hi=hist.h_count_hi.at[:, dst].set(hct_hi),
        h_count_lo=hist.h_count_lo.at[:, dst].set(hct_lo),
        h_sum_hi=hist.h_sum_hi.at[:, dst].set(hs_hi),
        h_sum_lo=hist.h_sum_lo.at[:, dst].set(hs_lo),
    )


roll_tiers = partial(
    jax.jit, static_argnames=("hspec",),
    donate_argnames=("hist",))(roll_tiers_core)


def read_column_core(hist: HistoryState, col, cidx, gidx, stidx, *,
                     hspec: HistorySpec):
    """Gather one ring column's counter/gauge/status values for a row
    subset — the watch tier's "previous interval" lookback (ISSUE 18
    satellite: delta watches read the ring instead of retained Python
    state). Pads ride mode="clip" gathers; the caller trims."""
    def grab(arr, idx):
        rows = jnp.take(arr, idx, axis=0, mode="clip")
        return jax.lax.dynamic_index_in_dim(rows, col, axis=1,
                                            keepdims=False)

    return (grab(hist.counter_hi, cidx), grab(hist.counter_lo, cidx),
            grab(hist.gauge, gidx), grab(hist.status, stidx))


read_column = partial(
    jax.jit, static_argnames=("hspec",))(read_column_core)
