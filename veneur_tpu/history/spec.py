"""Shape contract for the on-device history tier.

The history tier keeps the last K flush intervals device-resident as a
packed per-key ring in HBM (ROADMAP item 4): two-float counters, LWW
gauges/status, 6-bit packed HLL rows and per-window merged digest
centroids. `HistorySpec` is the frozen, hashable shape descriptor every
history jit specializes on — the same role `TableSpec` plays for the
ingest/flush programs, and deliberately a SEPARATE type: history
configuration must not perturb the snapshot `schema_hash` (which covers
DeviceState/TableSpec only), so history-off and history-on servers can
restore each other's checkpoints.

Ring layout (per kind, per row):

    col = tier * windows + (slot % windows)

Tier 0 holds raw flush intervals; tier t >= 1 holds 2x-decimated merges
of tier t-1 (slot m covers tier-(t-1) slots 2m and 2m+1), so `windows`
instants per tier buy `windows * 2^tiers` intervals of total lookback in
`windows * (tiers + 1)` resident columns. Error bound under decimation:
counters/counts/sums merge with compensated two-float adds (error-free
to ~48 significand bits — utils/numerics.py); HLL registers merge by
max (exact union); digests re-merge centroids through the same k-cell
compression as ingest, so windowed quantiles stay within the t-digest
merge bound (arxiv 1902.04023) with compression fixed by this spec;
gauges/status are last-writer-wins (the newer window's value survives a
merge — exact for "latest value" semantics, lossy by design for
anything else).
"""

from __future__ import annotations

from dataclasses import dataclass, asdict

from veneur_tpu.ops import hll
from veneur_tpu.ops import tdigest as td


@dataclass(frozen=True)
class HistorySpec:
    """Static shape parameters for one history ring. Hashable: used as a
    static jit argument by every history device program."""

    windows: int = 90           # K0: ring length per tier, in windows
    tiers: int = 3              # decimation tiers beyond tier 0
    counter_rows: int = 1 << 10
    gauge_rows: int = 1 << 9
    status_rows: int = 1 << 8
    set_rows: int = 1 << 8
    histo_rows: int = 1 << 8
    # History digests are re-merged many times (once per decimation
    # level and once per range query), so they run a SMALLER compression
    # than the live table: ~32 centroids per window keeps the histo ring
    # inside budget while the k-cell invariant bounds quantile error.
    compression: float = 20.0
    cells_per_k: int = 2
    exact_extremes: int = 4
    hll_precision: int = hll.DEFAULT_PRECISION

    @property
    def total_cols(self) -> int:
        return self.windows * (self.tiers + 1)

    @property
    def centroids(self) -> int:
        return td.centroid_capacity(self.compression, self.cells_per_k,
                                    self.exact_extremes)

    @property
    def hll_words(self) -> int:
        return hll.packed_words(self.hll_precision)

    @property
    def span_intervals(self) -> int:
        """Total lookback in flush intervals: tier `tiers` retains
        `windows` slots of 2^tiers intervals each."""
        return self.windows * (1 << self.tiers)

    def rows_for(self, kind_idx: int) -> int:
        return (self.counter_rows, self.gauge_rows, self.status_rows,
                self.set_rows, self.histo_rows)[kind_idx]

    def hbm_bytes(self) -> int:
        """Analytic device-resident footprint of one HistoryState, in
        bytes — the number `veneur.history.hbm_bytes` reports and the
        bench's K=90 @ 1M-keys cap gates on."""
        w = self.total_cols
        f32 = 4
        counter = self.counter_rows * w * 2 * f32          # hi + lo
        gauge = self.gauge_rows * w * f32
        status = self.status_rows * w * f32
        sets = self.set_rows * w * self.hll_words * f32
        # mean + weight centroid planes, plus min/max/count-pair/sum-pair
        histo = self.histo_rows * w * (2 * self.centroids + 6) * f32
        return counter + gauge + status + sets + histo

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "HistorySpec":
        return cls(**{k: d[k] for k in cls.__dataclass_fields__
                      if k in d})

    @classmethod
    def for_table(cls, table_spec, *, windows: int = 90, tiers: int = 3,
                  max_keys: int | None = None) -> "HistorySpec":
        """Derive a ring spec from the live TableSpec: the HLL precision
        MUST match (history stores the flush program's packed rows
        verbatim), per-kind row caps default to the live capacities
        clamped to `max_keys` (counters dominate real fleets; sketch
        kinds get smaller rings because their per-row window cost is
        2-3 orders of magnitude higher — see hbm_bytes)."""
        cap = max_keys if max_keys is not None else 1 << 20

        def rows(n, ceiling):
            return max(64, min(int(n), int(ceiling), cap))

        return cls(
            windows=int(windows), tiers=int(tiers),
            counter_rows=rows(table_spec.counter_capacity, 1 << 20),
            gauge_rows=rows(table_spec.gauge_capacity, 1 << 18),
            status_rows=rows(table_spec.status_capacity, 1 << 16),
            # a packed p=14 HLL row costs hll_words*4 = 12 KiB per
            # RESIDENT WINDOW (~4.3 MiB per key at K=90/tiers=3), so the
            # set ring's ceiling is far below the other sketch kinds:
            # 256 rows keep the whole K=90 @ 1M-key ring inside the
            # single-chip HBM budget config14_range_dashboard gates on
            set_rows=rows(table_spec.set_capacity, 1 << 8),
            histo_rows=rows(table_spec.histo_capacity, 1 << 14),
            hll_precision=table_spec.hll_precision,
        )
