"""Windowed-merge programs for range queries over the history ring.

One device program answers a whole batch of range queries: for every
requested STEP (a [t0, t1] slice of the lookback) the host selects the
minimal cover set of ring columns (writer.py plan_range) and ships a
{0,1} selection mask per step; the device folds the selected columns
per kind —

    counters / counts / sums   compensated two-float fold, ascending
                               column order (deterministic)
    gauges / status            last-writer-wins via a recency-rank
                               argmax over finite selected columns
    sets                       masked 6-bit register max (the Pallas
                               kernel in ops/pallas_history.py when its
                               probe passes, the XLA fori chain
                               otherwise — bit-identical packed words)
    histos                     selected centroids re-compressed through
                               the ring's own k-cell compression, then
                               the shared quantile kernel

— and ships one packed f32 buffer back, exactly the flush program's
wire discipline (step.py _pack_outputs / unpack_flush). The combined
entry point `query_combined` evaluates an instant-query batch and a
range batch in ONE launch, which is what lets POST /query coalesce
both shapes into a single device program.

Byte-exactness contract: a range answer must equal re-merging the
archived flush frames. That holds by construction because the replay
oracle (tests/test_history.py, benchmarks config14) feeds the archived
frames through the SAME write/roll programs into a fresh ring and asks
the SAME merge program — every float op runs in the same order on the
same bits.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from veneur_tpu.aggregation.step import _pack_outputs
from veneur_tpu.history.device import HistoryState
from veneur_tpu.history.spec import HistorySpec
from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.ops import tdigest as td
from veneur_tpu.utils.numerics import twofloat_merge

# A range batch pads its step count to a power of two (min 4, cap 32)
# so arbitrary dashboards hit a handful of compiled variants — the same
# bucketing idea as pack_query_inputs' n_q padding.
MAX_STEPS = 32


def _merge_windows_xla(rows, sel, *, precision: int):
    """XLA fallback for the masked window merge: fori over columns,
    dense u8 register max under the step mask. rows i32[N, W, nw],
    sel f32[S, W] -> i32[N, S, nw] packed."""
    n, w, _nw = rows.shape
    s = sel.shape[0]
    r = hll_ops.num_registers(precision)

    def body(i, acc):
        words = jax.lax.dynamic_index_in_dim(rows, i, axis=1,
                                             keepdims=False)
        regs = hll_ops.unpack_registers(
            words, precision=precision).astype(jnp.int32)
        m = jax.lax.dynamic_index_in_dim(sel, i, axis=1, keepdims=False)
        cand = jnp.maximum(acc, regs[:, None, :])
        return jnp.where((m > 0.0)[None, :, None], cand, acc)

    acc = jax.lax.fori_loop(0, w, body,
                            jnp.zeros((n, s, r), jnp.int32))
    return hll_ops.pack_registers(acc.astype(jnp.uint8),
                                  precision=precision)


def merge_windows(rows, sel, *, precision: int):
    """Masked window merge with the PR-8 gating pattern: Pallas kernel
    when its one-time probe passes on a real TPU, XLA chain otherwise.
    Both return identical packed words (integer max commutes with the
    6-bit packing), asserted in tests via interpret mode."""
    from veneur_tpu.ops import pallas_history
    if pallas_history.enabled():
        return pallas_history.merge_windows_packed(rows, sel,
                                                   precision=precision)
    return _merge_windows_xla(rows, sel, precision=precision)


def _fold_pair(hi_rows, lo_rows, sel):
    """Masked compensated fold of two-float pairs over the column axis:
    hi/lo f32[N, W], sel f32[S, W] -> (hi, lo) f32[N, S]. Ascending
    column order, fixed at trace time — the deterministic 'XLA chain'."""
    n = hi_rows.shape[0]
    s, w = sel.shape

    def body(i, carry):
        hi, lo = carry
        m = jax.lax.dynamic_index_in_dim(sel, i, axis=1, keepdims=False)
        xh = jax.lax.dynamic_index_in_dim(hi_rows, i, axis=1,
                                          keepdims=False)
        xl = jax.lax.dynamic_index_in_dim(lo_rows, i, axis=1,
                                          keepdims=False)
        return twofloat_merge(hi, lo, xh[:, None] * m[None, :],
                              xl[:, None] * m[None, :])

    z = jnp.zeros((n, s), jnp.float32)
    return jax.lax.fori_loop(0, w, body, (z, z))


def _lww(rows, sel, rank):
    """Last-writer-wins over selected finite columns: rows f32[N, W],
    sel f32[S, W], rank f32[W] (larger = newer) -> f32[N, S]; NaN when
    no selected column holds a value."""
    fin = jnp.isfinite(rows)                                  # [N, W]
    eff = jnp.where(fin[:, None, :] & (sel[None, :, :] > 0.0),
                    rank[None, None, :], -jnp.inf)            # [N, S, W]
    i = jnp.argmax(eff, axis=2)                               # [N, S]
    v = jnp.take_along_axis(
        jnp.broadcast_to(rows[:, None, :], eff.shape), i[..., None],
        axis=2)[..., 0]
    return jnp.where(jnp.max(eff, axis=2) == -jnp.inf,
                     jnp.float32(jnp.nan), v)


def range_merge_core(hist: HistoryState, qs, cidx, gidx, stidx, setidx,
                     hidx, sel, rank, *, hspec: HistorySpec):
    take = lambda a, i: jnp.take(a, i, axis=0, mode="clip")  # noqa: E731
    s = sel.shape[0]

    chi, clo = _fold_pair(take(hist.counter_hi, cidx),
                          take(hist.counter_lo, cidx), sel)
    gauge = _lww(take(hist.gauge, gidx), sel, rank)
    status = _lww(take(hist.status, stidx), sel, rank)

    merged = merge_windows(take(hist.hll, setidx), sel,
                           precision=hspec.hll_precision)
    est = hll_ops.estimate_packed_rows(merged,
                                       precision=hspec.hll_precision)

    mean = take(hist.h_mean, hidx)          # [bh, W, C]
    weight = take(hist.h_weight, hidx)
    hmin = take(hist.h_min, hidx)           # [bh, W]
    hmax = take(hist.h_max, hidx)
    bh = mean.shape[0]
    w = mean.shape[1]
    c = mean.shape[2]
    hq_steps, mn_steps, mx_steps = [], [], []
    for i in range(s):                       # static step count
        m = sel[i]                           # [W]
        wm = weight * m[None, :, None]
        cm, cw = td.compress_rows(
            mean.reshape(bh, w * c), wm.reshape(bh, w * c),
            compression=hspec.compression, cells_per_k=hspec.cells_per_k,
            out_c=hspec.centroids, exact_extremes=hspec.exact_extremes)
        mn = jnp.min(jnp.where(m[None, :] > 0, hmin, jnp.inf), axis=1)
        mx = jnp.max(jnp.where(m[None, :] > 0, hmax, -jnp.inf), axis=1)
        table = td.TDigestTable(
            mean=cm, weight=cw, min=mn, max=mx,
            count_hi=jnp.zeros((bh,), jnp.float32),
            count_lo=jnp.zeros((bh,), jnp.float32),
            sum_hi=jnp.zeros((bh,), jnp.float32),
            sum_lo=jnp.zeros((bh,), jnp.float32),
            recip_hi=jnp.zeros((bh,), jnp.float32),
            recip_lo=jnp.zeros((bh,), jnp.float32))
        hq_steps.append(td.quantiles(table, qs))
        mn_steps.append(mn)
        mx_steps.append(mx)
    hct_hi, hct_lo = _fold_pair(take(hist.h_count_hi, hidx),
                                take(hist.h_count_lo, hidx), sel)
    hs_hi, hs_lo = _fold_pair(take(hist.h_sum_hi, hidx),
                              take(hist.h_sum_lo, hidx), sel)
    return {
        "r_counter_hi": chi, "r_counter_lo": clo,
        "r_gauge": gauge, "r_status": status,
        "r_set_estimate": est,
        "r_histo_quantiles": jnp.stack(hq_steps, axis=1),
        "r_histo_min": jnp.stack(mn_steps, axis=1),
        "r_histo_max": jnp.stack(mx_steps, axis=1),
        "r_histo_count_hi": hct_hi, "r_histo_count_lo": hct_lo,
        "r_histo_sum_hi": hs_hi, "r_histo_sum_lo": hs_lo,
    }


def _range_in_packed_core(hist: HistoryState, hflat, *,
                          hspec: HistorySpec, n_q: int, n_steps: int,
                          buckets: tuple):
    """Packed-wire wrapper: hflat is ONE i32 buffer of
    [qs-bits | 5 row buckets | sel-bits | rank-bits] (pack_range_inputs
    builds it), the D2H side is one packed f32 buffer — the flush
    program's one-transfer-each-way discipline."""
    w = hspec.total_cols
    qs = jax.lax.bitcast_convert_type(hflat[:n_q], jnp.float32)
    idx, off = [], n_q
    for n in buckets:
        idx.append(hflat[off:off + n])
        off += n
    sel = jax.lax.bitcast_convert_type(
        hflat[off:off + n_steps * w], jnp.float32).reshape(n_steps, w)
    off += n_steps * w
    rank = jax.lax.bitcast_convert_type(hflat[off:off + w], jnp.float32)
    out = range_merge_core(hist, qs, *idx, sel, rank, hspec=hspec)
    return _pack_outputs(out)


range_in_packed = partial(
    jax.jit, static_argnames=("hspec", "n_q", "n_steps", "buckets"))(
        _range_in_packed_core)


def _query_combined_core(state, flat, hist, hflat, *, spec, n_q: int,
                         buckets: tuple, hspec: HistorySpec, hn_q: int,
                         hsteps: int, hbuckets: tuple):
    from veneur_tpu.aggregation.step import _flush_live_in_packed_core
    inst = _flush_live_in_packed_core(state, flat, spec=spec, n_q=n_q,
                                      buckets=buckets)
    rng = _range_in_packed_core(hist, hflat, hspec=hspec, n_q=hn_q,
                                n_steps=hsteps, buckets=hbuckets)
    return inst, rng


# One launch for a mixed instant+range batch: the query batcher
# dispatches this when a coalesced POST /query batch carries both
# shapes (query/engine.py _launch_on_pipeline).
query_combined = partial(
    jax.jit, static_argnames=("spec", "n_q", "buckets", "hspec",
                              "hn_q", "hsteps", "hbuckets"))(
        _query_combined_core)


def pad_steps(n: int) -> int:
    p = 4
    while p < n:
        p <<= 1
    return min(p, MAX_STEPS)


def pad_rows(n: int, cap: int) -> int:
    p = 4
    while p < n:
        p <<= 1
    return min(p, max(cap, 1))


def pack_range_inputs(hspec: HistorySpec, need, sel, rank, union_qs):
    """Host side: the range batch's gather plan -> (hflat, n_q, n_steps,
    buckets, qcol). `need` is (counter, gauge, status, set, histo) row
    lists in batch-match order; `sel` f32[S, W] selection masks from
    writer.plan_range; `rank` f32[W] recency ranks; `union_qs` the
    batch's union quantile set. Steps and quantiles pad to powers of
    two so variants stay bounded; pad steps carry all-zero masks and
    render as empty (host discards)."""
    import numpy as np
    w = hspec.total_cols
    qs = sorted(union_qs) or [0.5]
    n_q = 4
    while n_q < len(qs):
        n_q <<= 1
    qcol = {v: i for i, v in enumerate(qs)}
    qs_padded = np.asarray(qs + [0.5] * (n_q - len(qs)), np.float32)
    s_real = sel.shape[0]
    n_steps = pad_steps(s_real)
    if s_real > n_steps:
        raise ValueError("range step count exceeds MAX_STEPS")
    sel_p = np.zeros((n_steps, w), np.float32)
    sel_p[:s_real] = sel
    caps = tuple(hspec.rows_for(k) for k in range(5))
    buckets, idx_arrays = [], []
    for rows_list, cap in zip(need, caps):
        b = pad_rows(len(rows_list), cap)
        if len(rows_list) > b:
            raise ValueError("range gather exceeds history capacity")
        arr = np.zeros(b, np.int32)
        arr[:len(rows_list)] = rows_list
        buckets.append(b)
        idx_arrays.append(arr)
    flat = np.concatenate(
        [qs_padded.view(np.int32)]
        + [a.ravel() for a in idx_arrays]
        + [sel_p.ravel().view(np.int32),
           np.asarray(rank, np.float32).ravel().view(np.int32)])
    return flat, n_q, n_steps, tuple(buckets), qcol


def range_shapes(hspec: HistorySpec, buckets: tuple, n_steps: int,
                 n_q: int) -> dict:
    """unpack_flush shape table for the packed range output."""
    bc, bg, bst, bs, bh = buckets
    f32 = "float32"
    return {
        "r_counter_hi": ((bc, n_steps), f32),
        "r_counter_lo": ((bc, n_steps), f32),
        "r_gauge": ((bg, n_steps), f32),
        "r_status": ((bst, n_steps), f32),
        "r_set_estimate": ((bs, n_steps), f32),
        "r_histo_quantiles": ((bh, n_steps, n_q), f32),
        "r_histo_min": ((bh, n_steps), f32),
        "r_histo_max": ((bh, n_steps), f32),
        "r_histo_count_hi": ((bh, n_steps), f32),
        "r_histo_count_lo": ((bh, n_steps), f32),
        "r_histo_sum_hi": ((bh, n_steps), f32),
        "r_histo_sum_lo": ((bh, n_steps), f32),
    }
