"""Host orchestration for the history ring: key admission, window
metadata, decimation scheduling, and every device dispatch that touches
the ring.

The writer lives at SERVER scope, not interval scope: its key index —
(kind, name, joined_tags) -> ring row, per kind — persists across
interval KeyTable swaps AND across live reshards, because a key's ring
row has nothing to do with its current table slot or owner shard. That
is the history tier's consistency model: windows are addressed by key
identity, the mesh layout is free to change under them, and a 4->8->2
reshard only affects WHERE the next flush's values come from, never
where they land.

Two write paths share one device program (device.write_window):

  - the FUSED path: Aggregator.compute_flush threads the ring through
    the flush program itself (step.py flush_live_hist_packed) — the
    interval's values land in their ring column with zero extra
    launches and zero extra host traffic;
  - the HOST-FED path: sharded/collective backends (whose flush already
    materializes result+raw on the host) and the replay oracle feed
    `record_frame`, which dispatches the standalone write_window jit on
    the same values.

Both store bit-identical bytes for the same frame, which is what makes
"range answers byte-exact vs re-merging the archived flush frames" hold
on every backend.

Locking: `_dlock` serializes ring dispatches and guards the ring
reference (write programs DONATE the ring; see device.py); readers
(range queries, watch lookbacks) dispatch under the same lock and
materialize outside it. `_dlock` is an RLock so begin/commit can hold
it across a tiled multi-block flush.
"""

from __future__ import annotations

import threading
import time
from typing import List, NamedTuple, Optional

import numpy as np

from veneur_tpu.history import device as hdev
from veneur_tpu.history.spec import HistorySpec
from veneur_tpu.observability import jaxruntime

# Ring kind order — identical to step.FLUSH_KEY_KIND's numbering.
KINDS = ("counter", "gauge", "status", "set", "histogram")
# Out-of-range row sentinel: scatter mode="drop" discards these writes.
SENTINEL = np.int32(1 << 30)
_SYNC_EVERY = 64


def _pad_pow2(vals, fill, floor: int = 4):
    b = floor
    while b < len(vals):
        b <<= 1
    arr = np.full(b, fill, np.int32)
    arr[:len(vals)] = vals
    return arr


class HistoryPlan(NamedTuple):
    """One interval's admission decisions (host only)."""
    dests: tuple      # per kind: i32[len(get_meta(kind))] ring rows
    resets: tuple     # per kind: list of reassigned rows to wipe
    col: int          # tier-0 ring column for this window
    seq: int
    ts: float


class RangeStep(NamedTuple):
    seq_lo: int
    seq_hi: int
    ts_lo: float
    ts_hi: float
    complete: bool    # False when part of the span fell off retention


class RangePlan(NamedTuple):
    sel: np.ndarray   # f32[S, W] column-selection mask per step
    rank: np.ndarray  # f32[W] recency rank (end_seq + 1; 0 = unset)
    steps: List[RangeStep]


class _ColMeta(NamedTuple):
    tier: int
    start: int        # first tier-0 seq covered (inclusive)
    end: int          # last tier-0 seq covered (inclusive)
    ts: float         # wall time of the newest covered window


class HistoryWriter:
    def __init__(self, hspec: HistorySpec, *, interval_s: float = 10.0,
                 c_writes=None, c_evictions=None, c_range=None,
                 g_hbm=None):
        self.spec = hspec
        self.interval_s = float(interval_s)
        self._dlock = threading.RLock()
        self._mlock = threading.RLock()
        self._hist: Optional[hdev.HistoryState] = None
        self._rows = [dict() for _ in KINDS]        # key -> row
        self._row_key = [dict() for _ in KINDS]     # row -> key
        self._free = [list(range(hspec.rows_for(k) - 1, -1, -1))
                      for k in range(len(KINDS))]
        self._last_seen = [np.full(hspec.rows_for(k), -1, np.int64)
                           for k in range(len(KINDS))]
        self._seq = 0
        self._cols: List[Optional[_ColMeta]] = [None] * hspec.total_cols
        self._c_writes = c_writes
        self._c_evictions = c_evictions
        self._c_range = c_range
        self._g_hbm = g_hbm
        self._sync = jaxruntime.SampledSync(_SYNC_EVERY)
        if g_hbm is not None:
            g_hbm.set(float(hspec.hbm_bytes()))

    # -- key index -----------------------------------------------------------
    @staticmethod
    def _key(meta):
        return (meta.kind, meta.name, meta.joined_tags)

    def _col_of(self, tier: int, slot: int) -> int:
        return tier * self.spec.windows + (slot % self.spec.windows)

    def _assign_kind(self, k: int, metas, seq: int):
        """Ring rows for one kind's get_meta list, in order. Admission
        is sticky (a known key keeps its row); overflow evicts the
        least-recently-flushed row not used by THIS interval; when every
        row is in current use the incoming key is turned away (counted
        as an eviction of the write — the ring is a bounded cache, not
        the source of truth)."""
        rows, rkey = self._rows[k], self._row_key[k]
        free, seen = self._free[k], self._last_seen[k]
        dest = np.full(len(metas), SENTINEL, np.int32)
        resets = []
        evict_order = None
        evict_pos = 0
        evictions = 0
        for i, (_slot, m) in enumerate(metas):
            key = self._key(m)
            row = rows.get(key)
            if row is None:
                if free:
                    row = free.pop()
                else:
                    if evict_order is None:
                        evict_order = np.argsort(seen, kind="stable")
                    row = None
                    while evict_pos < len(evict_order):
                        cand = int(evict_order[evict_pos])
                        evict_pos += 1
                        if seen[cand] < seq:     # not used this interval
                            row = cand
                            break
                    if row is None:
                        evictions += 1           # turned away at capacity
                        continue
                    old = rkey.pop(row, None)
                    if old is not None:
                        del rows[old]
                    resets.append(row)
                    evictions += 1
                rows[key] = row
                rkey[row] = key
            dest[i] = row
            seen[row] = seq
        return dest, resets, evictions

    def plan_flush(self, table, ts: Optional[float] = None) -> HistoryPlan:
        ts = time.time() if ts is None else ts
        with self._mlock:
            seq = self._seq
            dests, resets, ev = [], [], 0
            for k, kind in enumerate(KINDS):
                d, r, e = self._assign_kind(k, table.get_meta(kind), seq)
                dests.append(d)
                resets.append(r)
                ev += e
            if ev and self._c_evictions is not None:
                self._c_evictions.inc(ev)
            return HistoryPlan(tuple(dests), tuple(resets),
                               self._col_of(0, seq), seq, ts)

    # -- fused-flush protocol ------------------------------------------------
    def begin_flush(self, plan: HistoryPlan):
        """Enter the write critical section: wipe reassigned rows and
        hand the current ring to the flush program. MUST be paired with
        commit_flush or abort_flush."""
        self._dlock.acquire()
        try:
            hist = self._ensure_hist()
            if any(plan.resets):
                hist = hdev.wipe_rows(
                    hist, tuple(_pad_pow2(r, SENTINEL)
                                for r in plan.resets), hspec=self.spec)
                self._hist = hist
            return hist
        except BaseException:
            self._dlock.release()
            raise

    def commit_flush(self, plan: HistoryPlan, hist) -> None:
        try:
            self._hist = hist
            with self._mlock:
                self._cols[plan.col] = _ColMeta(0, plan.seq, plan.seq,
                                                plan.ts)
                self._roll(plan)
                self._seq = plan.seq + 1
            if self._c_writes is not None:
                n = sum(int((d != SENTINEL).sum()) for d in plan.dests)
                self._c_writes.inc(n)
        finally:
            self._dlock.release()

    def abort_flush(self) -> None:
        self._dlock.release()

    def _ensure_hist(self) -> hdev.HistoryState:
        if self._hist is None:
            self._hist = hdev.empty_history(self.spec)
        return self._hist

    def _roll(self, plan: HistoryPlan) -> None:
        """Dispatch this window's due decimation merges (2x per tier):
        after window seq, tier t rolls when seq+1 is a multiple of 2^t.
        Column indices are traced scalars — one executable total."""
        s = plan.seq
        for t in range(1, self.spec.tiers + 1):
            if (s + 1) % (1 << t):
                break
            m = (s + 1) // (1 << t) - 1
            lo = 2 * m
            src0 = self._col_of(t - 1, lo)
            src1 = self._col_of(t - 1, lo + 1)
            dst = self._col_of(t, m)
            m0, m1 = self._cols[src0], self._cols[src1]
            step = 1 << (t - 1)
            if (m0 is None or m1 is None or m0.tier != t - 1
                    or m1.tier != t - 1 or m0.start != lo * step
                    or m1.start != (lo + 1) * step):
                continue      # partial ring (fresh start / old restore)
            self._hist = hdev.roll_tiers(
                self._hist, np.int32(src0), np.int32(src1),
                np.int32(dst), hspec=self.spec)
            self._cols[dst] = _ColMeta(t, m * (1 << t),
                                       (m + 1) * (1 << t) - 1, m1.ts)

    # -- host-fed path (sharded/collective backends, replay oracle) ----------
    def record_frame(self, table, result: dict, raw: dict,
                     ts: Optional[float] = None) -> None:
        """Write one archived flush frame (result+raw in get_meta
        order, as compute_flush(want_raw=True) returns them) into the
        ring via the standalone write_window jit."""
        plan = self.plan_flush(table, ts)
        hist = self.begin_flush(plan)
        try:
            vals, dests = self._frame_vals(plan, result, raw)
            hist = hdev.write_window(hist, vals, dests,
                                     np.int32(plan.col),
                                     hspec=self.spec, clear=True)
        except BaseException:
            self.abort_flush()
            raise
        self.commit_flush(plan, hist)

    @staticmethod
    def _split_pair(v):
        """f64 -> normalized (hi, lo) f32 pair; exact inverse of the
        host-side hi+lo combine for pairs the device normalized."""
        hi = np.asarray(v, np.float64).astype(np.float32)
        lo = (np.asarray(v, np.float64) - hi.astype(np.float64)).astype(
            np.float32)
        return hi, lo

    def _frame_vals(self, plan: HistoryPlan, result: dict, raw: dict):
        def bucket(arr, dest, fill=0.0):
            arr = np.asarray(arr)
            b = len(_pad_pow2(dest, SENTINEL, floor=64))
            out = np.full((b,) + arr.shape[1:], fill, arr.dtype)
            out[:len(arr)] = arr
            return out

        dc, dg, dst_, ds, dh = plan.dests
        chi, clo = self._split_pair(result["counter"])
        hct_hi, hct_lo = self._split_pair(result["histo_count"])
        hs_hi, hs_lo = self._split_pair(result["histo_sum"])
        vals = {
            "counter_hi": bucket(chi, dc),
            "counter_lo": bucket(clo, dc),
            "gauge": bucket(np.asarray(raw["gauge"], np.float32), dg),
            "status": bucket(np.asarray(result["status"], np.float32),
                             dst_),
            "hll": bucket(np.asarray(raw["hll"], np.int32), ds),
            "h_mean": bucket(np.asarray(raw["h_mean"], np.float32), dh),
            "h_weight": bucket(np.asarray(raw["h_weight"], np.float32),
                               dh),
            "h_min": bucket(np.asarray(raw["h_min"], np.float32), dh),
            "h_max": bucket(np.asarray(raw["h_max"], np.float32), dh),
            "h_count_hi": bucket(hct_hi, dh),
            "h_count_lo": bucket(hct_lo, dh),
            "h_sum_hi": bucket(hs_hi, dh),
            "h_sum_lo": bucket(hs_lo, dh),
        }
        dests = tuple(_pad_pow2(d, SENTINEL, floor=64)
                      for d in plan.dests)
        return vals, dests

    # -- reads ---------------------------------------------------------------
    @property
    def armed(self) -> bool:
        return self._hist is not None

    @property
    def seq(self) -> int:
        return self._seq

    def acquire_read(self):
        """Enter the dispatch critical section and return the current
        ring. Pair with release_read() AFTER dispatching (not after
        materializing — enqueued executions keep their buffers alive
        through donation)."""
        self._dlock.acquire()
        return self._ensure_hist()

    def release_read(self) -> None:
        self._dlock.release()

    def tick_sync(self, token) -> None:
        self._sync.tick(token)

    def iter_keys(self):
        """[(kind_idx, (kind, name, joined_tags), row)] snapshot of the
        admission index."""
        with self._mlock:
            return [(k, key, row) for k in range(len(KINDS))
                    for key, row in self._rows[k].items()]

    def rows_for_keys(self, k: int, keys):
        with self._mlock:
            return [self._rows[k].get(key) for key in keys]

    def read_values(self, seq: int, items):
        """Scalar-kind lookback for the watch tier: items is a list of
        (kind_idx, row) with kind_idx in {0 counter, 1 gauge,
        2 status}; returns f64[len(items)], NaN where window `seq` is
        not resident at tier 0 or the row is unset."""
        out = np.full(len(items), np.nan, np.float64)
        if not items:
            return out
        with self._mlock:
            col = self._col_of(0, seq)
            meta = self._cols[col]
            if (meta is None or meta.tier != 0 or meta.start != seq
                    or not self.armed):
                return out
        by_kind = [[], [], []]
        backrefs = [[], [], []]
        for i, (k, row) in enumerate(items):
            if k <= 2 and row is not None:
                by_kind[k].append(row)
                backrefs[k].append(i)
        idx = [_pad_pow2(b, 0) for b in by_kind]
        with self._dlock:
            hist = self._ensure_hist()
            chi, clo, g, st = hdev.read_column(
                hist, np.int32(col), idx[0], idx[1], idx[2],
                hspec=self.spec)
            self._sync.tick(st)
        chi = np.asarray(chi, np.float64)
        clo = np.asarray(clo, np.float64)
        g = np.asarray(g)
        st = np.asarray(st)
        for j, i in enumerate(backrefs[0]):
            out[i] = chi[j] + clo[j]
        for j, i in enumerate(backrefs[1]):
            out[i] = g[j]
        for j, i in enumerate(backrefs[2]):
            out[i] = st[j]
        return out

    # -- range planning ------------------------------------------------------
    def plan_range(self, range_s: float, window_s: Optional[float],
                   step_s: Optional[float],
                   max_steps: int) -> RangePlan:
        """Translate a [now - range_s, now] request into per-step column
        cover sets. Times quantize to flush intervals; each step's cover
        is the binary decomposition of its seq span over the decimation
        tiers (largest resident tier first), so a step touches
        O(tiers + log windows) columns instead of one per interval."""
        if self._c_range is not None:
            self._c_range.inc()
        iv = max(self.interval_s, 1e-9)
        with self._mlock:
            last = self._seq - 1
            n_back = max(1, int(round(range_s / iv)))
            step_w = max(1, int(round((step_s or range_s) / iv)))
            win_w = max(1, int(round((window_s or step_s or range_s)
                                     / iv)))
            w = self.spec.total_cols
            sel_rows, steps = [], []
            j = 0
            while j * step_w < n_back and len(steps) < max_steps:
                hi = last - j * step_w
                lo = hi - win_w + 1
                j += 1
                if hi < 0:
                    break
                row = np.zeros(w, np.float32)
                complete = self._cover(row, max(lo, 0), hi)
                if lo < 0:
                    complete = False
                sel_rows.append(row)
                steps.append(RangeStep(
                    max(lo, 0), hi,
                    self._ts_of(max(lo, 0), first=True),
                    self._ts_of(hi, first=False), complete))
            if not steps:
                sel_rows = [np.zeros(w, np.float32)]
                steps = [RangeStep(0, -1, 0.0, 0.0, False)]
            rank = np.zeros(w, np.float32)
            for c, m in enumerate(self._cols):
                if m is not None:
                    rank[c] = float(m.end + 1)
            return RangePlan(np.stack(sel_rows), rank, steps)

    def _cover(self, row: np.ndarray, lo: int, hi: int) -> bool:
        """Mark the minimal resident cover of tier columns for the
        inclusive seq span [lo, hi] in `row`; returns True iff the whole
        span was resident."""
        complete = True
        cur = hi
        while cur >= lo:
            placed = False
            # largest tier whose aligned block ends at `cur` and fits
            for t in range(self.spec.tiers, -1, -1):
                size = 1 << t
                if (cur + 1) % size or cur - size + 1 < lo:
                    continue
                m = (cur + 1) // size - 1
                col = self._col_of(t, m)
                meta = self._cols[col]
                if (meta is not None and meta.tier == t
                        and meta.start == m * size):
                    row[col] = 1.0
                    cur -= size
                    placed = True
                    break
            if not placed:
                complete = False
                cur -= 1
        return complete

    def _ts_of(self, seq: int, *, first: bool) -> float:
        col = self._col_of(0, seq)
        m = self._cols[col]
        if m is not None and m.tier == 0 and m.start == seq:
            return m.ts - (self.interval_s if first else 0.0)
        return 0.0

    # -- persistence ---------------------------------------------------------
    def snapshot(self) -> dict:
        """Checkpoint payload: host metadata + the ring arrays as numpy
        (byte-exact round trip; persistence/codec.py writes the arrays
        as binary chunks, the metadata as a JSON chunk)."""
        with self._dlock, self._mlock:
            hist = self._ensure_hist()
            arrays = {name: np.asarray(getattr(hist, name))
                      for name in hdev.HISTORY_FIELDS}
            meta = {
                "spec": self.spec.to_dict(),
                "seq": self._seq,
                "interval_s": self.interval_s,
                "cols": [list(m) if m is not None else None
                         for m in self._cols],
                "keys": [[[row, list(key)]
                          for key, row in self._rows[k].items()]
                         for k in range(len(KINDS))],
                "last_seen": [self._last_seen[k].tolist()
                              for k in range(len(KINDS))],
            }
        return {"meta": meta, "arrays": arrays}

    def restore(self, data: dict) -> None:
        """Adopt a checkpointed ring. A spec mismatch (different shape
        parameters on the restoring server) keeps the fresh empty ring —
        history is a cache; correctness never depends on it."""
        import jax.numpy as jnp
        meta = data.get("meta") or {}
        if HistorySpec.from_dict(meta.get("spec") or {}) != self.spec:
            return
        arrays = data.get("arrays") or {}
        if sorted(arrays) != sorted(hdev.HISTORY_FIELDS):
            return
        with self._dlock, self._mlock:
            self._hist = hdev.HistoryState(
                **{k: jnp.asarray(arrays[k]) for k in
                   hdev.HISTORY_FIELDS})
            self._seq = int(meta["seq"])
            self._cols = [(_ColMeta(*m) if m is not None else None)
                          for m in meta["cols"]]
            self._rows = [dict() for _ in KINDS]
            self._row_key = [dict() for _ in KINDS]
            for k in range(len(KINDS)):
                for row, key in meta["keys"][k]:
                    key = tuple(key)
                    self._rows[k][key] = int(row)
                    self._row_key[k][int(row)] = key
                self._last_seen[k] = np.asarray(meta["last_seen"][k],
                                                np.int64)
                used = set(self._row_key[k])
                self._free[k] = [r for r in
                                 range(self.spec.rows_for(k) - 1, -1, -1)
                                 if r not in used]
