"""Streaming watch tier: standing monitors as one fused device
evaluation per flush interval.

Clients register threshold / delta / quantile / cardinality watches
(Datadog-monitor-shaped: name/prefix/wildcard selector, predicate,
hysteresis band, `for_intervals` debounce) via `POST /watch`; a
compiler packs ALL active watches into one padded evaluation layout
over the flush program's own packed-input format, the engine runs it
as ONE `flush_live_in_packed` launch on each flush's detached interval
state, per-watch OK/ALERT/NO_DATA state machines step on the unpacked
rows, and only state TRANSITIONS fan out — over `GET /watch/stream`
(SSE, bounded per-subscriber queues with exact drop accounting) and an
optional webhook. Registrations and firing state ride the persistence
layer as a sidecar chunk, so monitors survive checkpoint/restore and
resharding. See README §Watches.
"""

from veneur_tpu.watch.compiler import (MAX_MATCHES, WatchPlan,
                                       compile_watches, resolve_watch)
from veneur_tpu.watch.engine import WatchEngine
from veneur_tpu.watch.model import (OPS, STATUSES, WATCH_KINDS, Watch,
                                    WatchError, WatchLimitError,
                                    parse_watch)
from veneur_tpu.watch.notify import (StreamHub, Subscriber,
                                     WebhookNotifier)

__all__ = [
    "MAX_MATCHES", "OPS", "STATUSES", "WATCH_KINDS", "Watch",
    "WatchEngine", "WatchError", "WatchLimitError", "WatchPlan",
    "StreamHub", "Subscriber", "WebhookNotifier", "compile_watches",
    "parse_watch", "resolve_watch",
]
