"""Transition fan-out: SSE stream hub + optional webhook sink.

Only STATE TRANSITIONS leave the watch engine — a watch holding ALERT
across a hundred intervals produces one event, not a hundred — so the
fan-out volume is bounded by alert dynamics, not by watch count.

`StreamHub` backs `GET /watch/stream`: each subscriber owns a bounded
deque; a publisher that finds it full drops the OLDEST queued event
(an SSE consumer that fell behind wants the newest state, and the
at-least-once contract is per TRANSITION STREAM, not per slow reader)
and every drop is counted under `veneur.watch.notify_dropped_total`
labeled with the dropped event's watch kind — exact accounting, one
inc per lost event, asserted by the storm tests.

`WebhookNotifier` rides the PR 1 ResilientSink harness: the POST runs
under the server's shared retry policy via `resilient_post`, and a
TERMINAL failure (retries exhausted) counts every event in the batch
as dropped. Delivery is therefore at-least-once per transition up to
the configured retry budget, never silently lossy.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from collections import deque
from typing import List, Optional

from veneur_tpu.sinks.base import ResilientSink

log = logging.getLogger("veneur_tpu.watch")

# per-subscriber queue depth: deep enough to ride a storm burst, small
# enough that an abandoned-but-open stream can't hold a storm's worth
# of event dicts per subscriber
SUBSCRIBER_QUEUE_DEPTH = 256


class Subscriber:
    """One SSE consumer's bounded event queue (drop-oldest)."""

    __slots__ = ("_dq", "_cv", "depth", "dropped")

    def __init__(self, depth: int = SUBSCRIBER_QUEUE_DEPTH) -> None:
        self._dq: deque = deque()
        self._cv = threading.Condition()
        self.depth = max(1, int(depth))
        self.dropped = 0   # this subscriber's exact drop count

    def offer(self, event: dict) -> Optional[dict]:
        """Enqueue; returns the DROPPED event when the queue was full
        (the caller accounts it), else None."""
        with self._cv:
            lost = None
            if len(self._dq) >= self.depth:
                lost = self._dq.popleft()
                self.dropped += 1
            self._dq.append(event)
            self._cv.notify()
            return lost

    def get(self, timeout: float) -> Optional[dict]:
        with self._cv:
            if not self._dq:
                self._cv.wait(timeout)
            if not self._dq:
                return None
            return self._dq.popleft()


class StreamHub:
    """Subscriber registry + transition publisher (engine thread)."""

    def __init__(self, max_subscribers: int, dropped=None,
                 depth: int = SUBSCRIBER_QUEUE_DEPTH) -> None:
        self.max_subscribers = max(1, int(max_subscribers))
        self._dropped = dropped   # veneur.watch.notify_dropped_total
        self._depth = depth
        self._lock = threading.Lock()
        self._subs: List[Subscriber] = []

    def subscribe(self) -> Optional[Subscriber]:
        """None when the subscriber cap is reached (HTTP 503)."""
        with self._lock:
            if len(self._subs) >= self.max_subscribers:
                return None
            sub = Subscriber(self._depth)
            self._subs.append(sub)
            return sub

    def unsubscribe(self, sub: Subscriber) -> None:
        with self._lock:
            try:
                self._subs.remove(sub)
            except ValueError:
                pass

    @property
    def n_subscribers(self) -> int:
        with self._lock:
            return len(self._subs)

    def publish(self, events: List[dict]) -> int:
        """Fan one interval's transitions out to every subscriber.
        Returns the total number of events dropped (all counted)."""
        with self._lock:
            subs = list(self._subs)
        n_dropped = 0
        for sub in subs:
            for ev in events:
                lost = sub.offer(ev)
                if lost is not None:
                    n_dropped += 1
                    if self._dropped is not None:
                        self._dropped.inc(
                            1, kind=lost.get("kind", "threshold"))
        return n_dropped


class WebhookNotifier(ResilientSink):
    """POST one JSON batch of transitions per evaluated interval to
    `watch_webhook_url`, under the server's shared retry/breaker
    harness. Runs on the watch engine thread — a slow webhook delays
    only subsequent WATCH intervals (which drop-oldest with exact
    accounting), never ingest or the flush deadline."""

    name = "watch_webhook"

    def __init__(self, url: str, dropped=None,
                 timeout_s: float = 10.0) -> None:
        self.url = url
        self._dropped = dropped
        self.timeout_s = timeout_s
        self.posts_total = 0
        self.post_failures = 0

    def post_events(self, events: List[dict]) -> bool:
        """True when the batch landed; on terminal failure every event
        counts as dropped (exact accounting) and delivery falls back to
        the SSE stream + the next checkpoint's persisted state."""
        if not events:
            return True
        body = json.dumps({"events": events}).encode()

        def _post():
            req = urllib.request.Request(
                self.url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                if resp.status >= 300:
                    raise RuntimeError(f"webhook status {resp.status}")

        try:
            if self.resilience_configured:
                self.resilient_post(_post, what="watch events")
            else:
                _post()
        except Exception as e:  # noqa: BLE001 — terminal failure accounted
            self.post_failures += 1
            if self._dropped is not None:
                for ev in events:
                    self._dropped.inc(
                        1, kind=ev.get("kind", "threshold"))
            log.warning("watch webhook %s failed (%d events dropped, "
                        "counted): %s", self.url, len(events), e)
            return False
        self.posts_total += 1
        return True
