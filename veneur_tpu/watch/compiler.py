"""Compile ALL active watches into one padded device evaluation.

The compiler is the reason 100k standing monitors cost one kernel
launch per interval instead of 100k queries: every watch's selector is
resolved against the interval's sorted NameIndex (the query tier's
bisect index over the detached KeyTable's meta prefix), the matched
rows are DEDUPED across watches into per-kind slot gathers, the union
of quantile requests becomes one quantile vector, and the whole thing
is packed with `pack_query_inputs` into the exact input layout the
flush program (`flush_live_in_packed`) already jits — so evaluation
reuses the compiled executable the flush and query tiers share, at a
bucket shape that only recompiles when the padded gather size crosses
a bucket boundary.

Re-resolution cost: swap() installs a FRESH KeyTable every interval,
so selector→row resolution is interval-scoped by construction — the
plan cache keys on (table identity, per-kind meta counts, watch-set
generation) and a new interval or a register/delete naturally misses.
That re-resolve (bisect per watch) runs on the WATCH ENGINE thread
against a detached table, never on the ingest pipeline or the flush
worker, so table growth and resharding cost the watch tier only its
own latency.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from veneur_tpu.query.nameindex import NameIndex
from veneur_tpu.query.snapshot import COUNT_TABLES

# per-watch resolution cap, the query tier's bound: a wildcard that
# explodes matches is truncated (worst-of over the first N) instead of
# letting one watch blow the padded gather past a flush block
MAX_MATCHES = 1024

# watch kind -> candidate count tables (threshold/delta restricted
# further by metric_kinds at resolve time)
_KIND_TABLES = {
    "threshold": ("counter", "gauge", "status"),
    "delta": ("counter", "gauge", "status"),
    "quantile": ("histo",),
    "cardinality": ("set",),
}
_SCALAR_TABLE = {"counter": "counter", "gauge": "gauge",
                 "status": "status"}


class WatchPlan:
    """One interval's packed evaluation: device inputs + the per-watch
    row map the engine walks to extract values from the unpacked flush
    result."""

    __slots__ = ("inputs", "n_q", "buckets", "qcol", "rows",
                 "truncated", "n_rows")

    def __init__(self, inputs, n_q: int, buckets: tuple, qcol: dict,
                 rows: Dict[int, List[Tuple[str, int]]],
                 truncated: set, n_rows: int) -> None:
        self.inputs = inputs
        self.n_q = n_q
        self.buckets = buckets
        self.qcol = qcol
        self.rows = rows            # wid -> [(tname, result row), ...]
        self.truncated = truncated  # wids whose selector hit MAX_MATCHES
        self.n_rows = n_rows        # total deduped gather rows


def _tables_for(watch) -> List[str]:
    if watch.kind in ("threshold", "delta") and watch.metric_kinds:
        return [_SCALAR_TABLE[k] for k in watch.metric_kinds]
    return list(_KIND_TABLES[watch.kind])


def resolve_watch(index: NameIndex, watch) -> Tuple[list, bool]:
    """Selector -> [(tname, pos, slot, meta)] via the sorted index,
    with the query tier's kind/tag filtering. Returns (matches,
    truncated)."""
    out = []
    for tname in _tables_for(watch):
        if watch.mode == "name":
            ent = index.exact(tname, watch.arg)
        elif watch.mode == "prefix":
            ent = index.prefix(tname, watch.arg)
        else:
            ent = index.match(tname, watch.arg)
        for pos, slot, meta in ent:
            # histo rows carry both histogram and timer metas; honor a
            # quantile watch's metric_kinds restriction by actual kind
            if (tname == "histo" and watch.metric_kinds
                    and meta.kind not in watch.metric_kinds):
                continue
            if watch.tags is not None and tuple(meta.tags) != watch.tags:
                continue
            out.append((tname, pos, slot, meta))
    truncated = len(out) > MAX_MATCHES
    if truncated:
        out = out[:MAX_MATCHES]
    return out, truncated


def compile_watches(spec, index: NameIndex, watches: list
                    ) -> Optional[WatchPlan]:
    """Pack every active watch into ONE evaluation layout. Returns None
    when no selector matched anything (the engine still steps each
    watch with value=None so NO_DATA tracking advances)."""
    need: Dict[str, List[int]] = {t: [] for t in COUNT_TABLES}
    rowof: Dict[Tuple[str, int], int] = {}
    rows: Dict[int, List[Tuple[str, int]]] = {}
    truncated: set = set()
    union_qs: set = set()
    for w in watches:
        ms, trunc = resolve_watch(index, w)
        if trunc:
            truncated.add(w.wid)
        lst = []
        for tname, pos, slot, _meta in ms:
            key = (tname, pos)
            r = rowof.get(key)
            if r is None:
                r = len(need[tname])
                rowof[key] = r
                need[tname].append(slot)
            lst.append((tname, r))
        rows[w.wid] = lst
        if w.kind == "quantile" and lst:
            union_qs.add(float(w.quantile))
    n_rows = sum(len(need[t]) for t in COUNT_TABLES)
    if n_rows == 0:
        return None
    from veneur_tpu.aggregation.step import pack_query_inputs
    inputs, n_q, buckets, qcol = pack_query_inputs(
        spec, [need[t] for t in COUNT_TABLES], union_qs)
    return WatchPlan(inputs, n_q, buckets, qcol, rows, truncated, n_rows)
