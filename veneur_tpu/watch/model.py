"""Watch registrations and per-watch alert state machines.

A watch is a STANDING query (Datadog-monitor-shaped): a selector over
metric names (exact name, prefix, or fnmatch wildcard — the query
tier's three modes), a predicate (`op` + `threshold`) over one derived
value per interval, and alerting dynamics:

- `hysteresis` — a recovery band. An up-watch (`>`/`>=`) that fired at
  `value > threshold` recovers only once `value <= threshold −
  hysteresis` (mirrored for down-watches), so a series oscillating on
  the threshold produces one transition pair, not one per interval.
- `for_intervals` — debounce. The predicate must breach on N
  CONSECUTIVE evaluated intervals before OK/NO_DATA becomes ALERT; a
  non-breaching interval resets the streak. Breaches that do not yet
  (or cannot — already ALERT, inside the band) transition are counted
  as `suppressed`, which is what makes the fired+suppressed accounting
  exact under a storm.
- `no_data_intervals` — after N consecutive intervals where the
  selector matched nothing (or every match was non-finite), the watch
  enters NO_DATA; any datapoint leaves it. 0 disables.

Four watch kinds, keyed to what the fused flush program computes:

- `threshold`  — counter / gauge / status scalar per interval;
- `delta`      — interval-over-interval difference of that scalar
  (the previous interval's raw value rides the persisted state; a
  data gap invalidates the baseline rather than alerting on a bogus
  jump across it);
- `quantile`   — one t-digest quantile of a histogram/timer row;
- `cardinality`— the packed-HLL set estimate.

A selector that matches several series reduces host-side to the
WORST-OF value for the predicate direction (max for `>`/`>=`, min for
`<`/`<=`): a prefix watch over a fleet fires when any member breaches,
without N per-member registrations.

Registration dicts and state dicts are built with a fixed key
insertion order so the persistence sidecar chunk (JSON) is
byte-reproducible: snapshot → restore → snapshot is the identity.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

WATCH_KINDS = ("threshold", "delta", "quantile", "cardinality")
OPS = (">", ">=", "<", "<=")
STATUSES = ("OK", "ALERT", "NO_DATA")

# metric kinds a scalar (threshold/delta) watch may select over, and
# the full set the query tier knows (histogram/timer share the histo
# device table; set rides cardinality; see query/engine.py KINDS)
_SCALAR_METRIC_KINDS = ("counter", "gauge", "status")
_HISTO_METRIC_KINDS = ("histogram", "timer")

_MAX_FOR_INTERVALS = 1000
_MAX_DESCRIPTION = 256


class WatchError(ValueError):
    """Client error in a watch registration body (HTTP 400)."""


class WatchLimitError(WatchError):
    """watch_max_active reached (HTTP 429) — a registration storm must
    not grow the packed evaluation past the configured ceiling."""


def _num(v, what: str) -> float:
    try:
        f = float(v)
    except (TypeError, ValueError):
        raise WatchError(f"{what} must be a number")
    if not math.isfinite(f):
        raise WatchError(f"{what} must be finite")
    return f


def parse_watch(body) -> dict:
    """Validated canonical registration dict from a client body. The
    returned dict uses a FIXED key order (see module docstring)."""
    if not isinstance(body, dict) or not body:
        raise WatchError("watch registration must be a JSON object")
    kind = body.get("kind", "threshold")
    if kind not in WATCH_KINDS:
        raise WatchError(f"kind must be one of {WATCH_KINDS}")
    modes = [k for k in ("name", "prefix", "match") if k in body]
    if len(modes) != 1:
        raise WatchError("a watch needs exactly one of name/prefix/match")
    mode = modes[0]
    arg = body[mode]
    if not isinstance(arg, str) or not arg:
        raise WatchError(f"{mode} must be a non-empty string")
    op = body.get("op", ">")
    if op not in OPS:
        raise WatchError(f"op must be one of {OPS}")
    if "threshold" not in body:
        raise WatchError("threshold is required")
    threshold = _num(body["threshold"], "threshold")
    hysteresis = _num(body.get("hysteresis", 0.0), "hysteresis")
    if hysteresis < 0:
        raise WatchError("hysteresis must be >= 0")
    try:
        for_intervals = int(body.get("for_intervals", 1))
        no_data_intervals = int(body.get("no_data_intervals", 0))
    except (TypeError, ValueError):
        raise WatchError("for_intervals/no_data_intervals must be integers")
    if not 1 <= for_intervals <= _MAX_FOR_INTERVALS:
        raise WatchError(
            f"for_intervals must be in 1..{_MAX_FOR_INTERVALS}")
    if no_data_intervals < 0:
        raise WatchError("no_data_intervals must be >= 0")
    metric_kinds = body.get("metric_kinds")
    if metric_kinds is not None:
        allowed = (_HISTO_METRIC_KINDS if kind == "quantile"
                   else _SCALAR_METRIC_KINDS if kind in ("threshold",
                                                         "delta")
                   else ("set",))
        if (not isinstance(metric_kinds, (list, tuple)) or not metric_kinds
                or any(k not in allowed for k in metric_kinds)):
            raise WatchError(
                f"metric_kinds for a {kind} watch must be drawn "
                f"from {allowed}")
        metric_kinds = list(metric_kinds)
    tags = body.get("tags")
    if tags is not None:
        if not isinstance(tags, (list, tuple)) \
                or any(not isinstance(t, str) for t in tags):
            raise WatchError("tags must be a list of strings")
        tags = list(tags)
    quantile = None
    if kind == "quantile":
        quantile = _num(body.get("quantile", 0.99), "quantile")
        if not 0.0 <= quantile <= 1.0:
            raise WatchError("quantile must lie in [0, 1]")
    elif "quantile" in body:
        raise WatchError("quantile only applies to quantile watches")
    description = body.get("description", "")
    if not isinstance(description, str) \
            or len(description) > _MAX_DESCRIPTION:
        raise WatchError(
            f"description must be a string of <= {_MAX_DESCRIPTION} chars")
    # FIXED key order — the persistence chunk serializes this dict
    out = {"kind": kind, mode: arg, "op": op, "threshold": threshold,
           "hysteresis": hysteresis, "for_intervals": for_intervals,
           "no_data_intervals": no_data_intervals}
    if metric_kinds is not None:
        out["metric_kinds"] = metric_kinds
    if tags is not None:
        out["tags"] = tags
    if quantile is not None:
        out["quantile"] = quantile
    if description:
        out["description"] = description
    return out


def _breach(op: str, value: float, threshold: float) -> bool:
    if op == ">":
        return value > threshold
    if op == ">=":
        return value >= threshold
    if op == "<":
        return value < threshold
    return value <= threshold


def _recovered(op: str, value: float, threshold: float,
               hysteresis: float) -> bool:
    """ALERT -> OK requires leaving the hysteresis band, not merely
    un-breaching: an up-watch recovers at threshold − hysteresis."""
    if hysteresis <= 0:
        return not _breach(op, value, threshold)
    if op in (">", ">="):
        return value <= threshold - hysteresis
    return value >= threshold + hysteresis


# sentinel distinguishing "no override passed" from an explicit None
# baseline (ring window absent) in Watch.observe
_UNSET = object()


class Watch:
    """One registration + its alert state. Mutated only on the watch
    engine thread (register/delete/restore swap whole dicts under the
    engine lock), so steps never race."""

    __slots__ = ("wid", "kind", "mode", "arg", "op", "threshold",
                 "hysteresis", "for_intervals", "no_data_intervals",
                 "metric_kinds", "tags", "quantile", "description",
                 "status", "streak", "empty_streak", "last_value",
                 "value", "last_change_ts")

    def __init__(self, wid: int, spec: dict) -> None:
        self.wid = int(wid)
        self.kind = spec["kind"]
        self.mode = next(k for k in ("name", "prefix", "match")
                         if k in spec)
        self.arg = spec[self.mode]
        self.op = spec["op"]
        self.threshold = float(spec["threshold"])
        self.hysteresis = float(spec["hysteresis"])
        self.for_intervals = int(spec["for_intervals"])
        self.no_data_intervals = int(spec["no_data_intervals"])
        mk = spec.get("metric_kinds")
        self.metric_kinds = tuple(mk) if mk else None
        tags = spec.get("tags")
        self.tags = tuple(tags) if tags is not None else None
        self.quantile = spec.get("quantile")
        self.description = spec.get("description", "")
        # alert state
        self.status = "OK"
        self.streak = 0          # consecutive breaching intervals
        self.empty_streak = 0    # consecutive no-match intervals
        self.last_value = None   # delta baseline (previous raw value)
        self.value = None        # last evaluated value (for listings)
        self.last_change_ts = 0  # interval ts of the last transition

    # -- evaluation ----------------------------------------------------------
    def reduce(self, values: List[float]) -> Optional[float]:
        """Worst-of reduction across a multi-match selector."""
        if not values:
            return None
        return max(values) if self.op in (">", ">=") else min(values)

    def observe(self, raw: Optional[float], ts: int, prev_override=_UNSET
                ) -> Tuple[Optional[Tuple[str, str]], bool]:
        """Advance one evaluated interval. Returns `(transition,
        suppressed)`: transition is `(old_status, new_status)` or None;
        suppressed is True when the predicate breached without causing
        a transition (debounce pending, or already ALERT inside the
        hysteresis hold). Exactly one of fired (a transition into
        ALERT) / suppressed is possible per breaching interval, which
        is the accounting invariant the storm tests pin.

        `prev_override` (delta watches): the previous interval's value
        as read back from the HISTORY RING (engine._delta_baselines) —
        the ring, not privately retained Python state, is the baseline
        of record when the history tier is on. None means the ring has
        no resident previous window (gap semantics, same as a lost
        baseline). Without the override the pre-history behavior is
        unchanged."""
        ts = int(ts)
        if raw is not None:
            # canonicalize to float so the persisted state (the delta
            # baseline in particular) serializes identically before and
            # after a checkpoint round trip
            raw = float(raw)
            if not math.isfinite(raw):
                raw = None
        if raw is None:
            self.empty_streak += 1
            self.streak = 0
            self.value = None
            if self.kind == "delta":
                self.last_value = None  # a gap invalidates the baseline
            if (self.no_data_intervals > 0
                    and self.empty_streak >= self.no_data_intervals
                    and self.status != "NO_DATA"):
                old, self.status = self.status, "NO_DATA"
                self.last_change_ts = ts
                return (old, "NO_DATA"), False
            return None, False
        self.empty_streak = 0
        if self.kind == "delta":
            if prev_override is _UNSET:
                prev, self.last_value = self.last_value, raw
            else:
                # ring-sourced baseline; keep last_value maintained so
                # the persisted state (and any history-off fallback
                # interval) stays coherent
                prev = prev_override
                self.last_value = raw
            if prev is None:
                # first datapoint primes the baseline; nothing to compare
                self.value = None
                self.streak = 0
                if self.status == "NO_DATA":
                    self.status = "OK"
                    self.last_change_ts = ts
                    return ("NO_DATA", "OK"), False
                return None, False
            value = raw - prev
        else:
            value = raw
        self.value = value
        breach = _breach(self.op, value, self.threshold)
        if self.status == "ALERT":
            if _recovered(self.op, value, self.threshold, self.hysteresis):
                self.status = "OK"
                self.streak = 0
                self.last_change_ts = ts
                return ("ALERT", "OK"), False
            # holding: a breach (or an in-band value) with no transition
            return None, breach
        was_no_data = self.status == "NO_DATA"
        if breach:
            self.streak += 1
            if self.streak >= self.for_intervals:
                old, self.status = self.status, "ALERT"
                self.last_change_ts = ts
                return (old, "ALERT"), False
            if was_no_data:
                self.status = "OK"
                self.last_change_ts = ts
                return ("NO_DATA", "OK"), True   # breach, debounce pending
            return None, True
        self.streak = 0
        if was_no_data:
            self.status = "OK"
            self.last_change_ts = ts
            return ("NO_DATA", "OK"), False
        return None, False

    # -- persistence ---------------------------------------------------------
    def to_dict(self) -> dict:
        """Registration view (FIXED key order — serialized into the
        checkpoint sidecar chunk)."""
        d = {"id": self.wid, "kind": self.kind, self.mode: self.arg,
             "op": self.op, "threshold": self.threshold,
             "hysteresis": self.hysteresis,
             "for_intervals": self.for_intervals,
             "no_data_intervals": self.no_data_intervals}
        if self.metric_kinds is not None:
            d["metric_kinds"] = list(self.metric_kinds)
        if self.tags is not None:
            d["tags"] = list(self.tags)
        if self.quantile is not None:
            d["quantile"] = self.quantile
        if self.description:
            d["description"] = self.description
        return d

    def state_dict(self) -> dict:
        """Firing state (FIXED key order, JSON-exact value types)."""
        return {"status": self.status, "streak": int(self.streak),
                "empty_streak": int(self.empty_streak),
                "last_value": self.last_value,
                "last_change_ts": int(self.last_change_ts)}

    def load_state(self, st: dict) -> None:
        status = st.get("status", "OK")
        if status not in STATUSES:
            raise WatchError(f"bad persisted status {status!r}")
        self.status = status
        self.streak = int(st.get("streak", 0))
        self.empty_streak = int(st.get("empty_streak", 0))
        lv = st.get("last_value")
        self.last_value = None if lv is None else float(lv)
        self.last_change_ts = int(st.get("last_change_ts", 0))

    def describe(self) -> dict:
        """Live listing view: registration + current state + last
        evaluated value (NOT persisted — `value` is derivable)."""
        d = self.to_dict()
        d["status"] = self.status
        d["streak"] = self.streak
        if self.value is not None:
            d["value"] = self.value
        d["last_change_ts"] = self.last_change_ts
        return d
