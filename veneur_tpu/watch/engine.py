"""Watch engine: one fused device evaluation per flush interval.

The engine owns its OWN thread and evaluates on the flush's DETACHED
interval state, which is what makes 100k standing monitors free on the
hot paths:

- swap() ran on the pipeline thread before the flush job was queued,
  so the state the flush worker hands to `offer()` is immutable — no
  later donating ingest step can invalidate it (the query tier's
  two-visit pipeline protocol exists precisely because LIVE state gets
  donated; detached state needs none of it, so watch evaluation never
  touches the packet queue at all);
- `offer()` is non-blocking by contract (bounded queue, drop-oldest
  with exact accounting), so the flush worker's deadline is untouched
  even when the watch thread is mid-launch;
- the evaluation itself is ONE `flush_live_in_packed` launch — the
  same jitted executable the flush and query tiers run — over the
  compiler's deduped packed gather, then host-side state-machine steps
  over the unpacked rows.

Accounting invariant (pinned by the storm tests): per active watch,
every interval the flush worker OFFERS is either evaluated
(`evaluated_total`) or counted as suppressed (`suppressed_total` — a
dropped-oldest backlog interval, an overload-CRITICAL skip, or an
engine failure); per breaching evaluated interval, exactly one of
`fired_total` (a transition into ALERT) or `suppressed_total`
(debounce pending / hysteresis hold) increments. Nothing is silent.

The dispatch site follows the query engine's vtlint discipline: launch
cost accumulates under `dispatch_ns` (enqueue-only by naming
convention) and device completion is sampled through
`jaxruntime.SampledSync` on this thread — never the pipeline's, never
the flush worker's.

During a live reshard the serving table answers before all moved rows
folded, so an interval evaluated mid-move may miss in-flight rows for
at most one flush interval; its transitions are MARKED stale_bounded,
mirroring the query tier's read contract.
"""

from __future__ import annotations

import logging
import math
import queue as queue_mod
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from veneur_tpu.observability import jaxruntime
from veneur_tpu.query.nameindex import NameIndex
from veneur_tpu.query.snapshot import _META_KIND, COUNT_TABLES
from veneur_tpu.watch.compiler import WatchPlan, compile_watches
from veneur_tpu.watch.model import (WATCH_KINDS, Watch, WatchError,
                                    WatchLimitError, parse_watch)
from veneur_tpu.watch.notify import StreamHub, WebhookNotifier

log = logging.getLogger("veneur_tpu.watch")

_SYNC_EVERY = 64       # sampled device-sync cadence (1 in N launches)
_JOB_DEPTH = 2         # detached intervals queued before drop-oldest
_CLOSE_TIMEOUT_S = 10.0


class WatchEngine:
    """Registry + evaluator + notifier for the streaming watch tier."""

    def __init__(self, server, *, max_active: int = 1 << 17,
                 max_subscribers: int = 64, webhook_url: str = "",
                 retry_policy=None, evaluated=None, fired=None,
                 suppressed=None, dropped=None, eval_ns=None,
                 active=None, history=None) -> None:
        self._server = server
        self.spec = server.aggregator.spec
        self._history = history          # HistoryWriter | None
        self.max_active = max(1, int(max_active))
        self._c_evaluated = evaluated
        self._c_fired = fired
        self._c_suppressed = suppressed
        self._c_eval_ns = eval_ns
        self._g_active = active
        # registry: wid -> Watch; mutations under _lock, state-machine
        # steps on the engine thread only
        self._lock = threading.Lock()
        self._watches: Dict[int, Watch] = {}
        # per-kind census maintained incrementally: the gauge update and
        # the skipped-interval accounting must stay O(kinds), not
        # O(active) — a 100k-watch bulk registration recounting the
        # whole registry per admit is O(n^2)
        self._active_by_kind: Dict[str, int] = {}
        self._next_id = 1
        self._generation = 0
        # packed-plan cache: one compile per (interval table, watch set)
        self._plan: Optional[WatchPlan] = None
        self._plan_key = None
        self._plan_table = None
        self._jobs: "queue_mod.Queue" = queue_mod.Queue(maxsize=_JOB_DEPTH)
        self._stop = threading.Event()
        self._sync = jaxruntime.SampledSync(_SYNC_EVERY)
        self.dispatch_ns = 0
        self.launches_total = 0
        self.intervals_evaluated = 0
        self.intervals_skipped = 0
        self.hub = StreamHub(max_subscribers, dropped=dropped)
        self.webhook: Optional[WebhookNotifier] = None
        if webhook_url:
            self.webhook = WebhookNotifier(webhook_url, dropped=dropped)
            if retry_policy is not None:
                self.webhook.configure_resilience(retry_policy)
        self._thread = threading.Thread(
            target=self._run, name="watch-engine", daemon=True)
        self._thread.start()

    # -- registry ------------------------------------------------------------
    def register(self, body) -> dict:
        """Parse + admit one watch. Raises WatchError (400) on a bad
        body, WatchLimitError (429) at watch_max_active."""
        spec = parse_watch(body)
        with self._lock:
            if len(self._watches) >= self.max_active:
                raise WatchLimitError(
                    f"watch_max_active={self.max_active} reached")
            wid = self._next_id
            self._next_id += 1
            w = Watch(wid, spec)
            self._watches[wid] = w
            self._active_by_kind[w.kind] = \
                self._active_by_kind.get(w.kind, 0) + 1
            self._generation += 1
        self._update_active_gauge()
        return w.to_dict()

    def delete(self, wid: int) -> bool:
        with self._lock:
            w = self._watches.pop(int(wid), None)
            found = w is not None
            if found:
                self._active_by_kind[w.kind] -= 1
                self._generation += 1
        if found:
            self._update_active_gauge()
        return found

    def list_watches(self) -> List[dict]:
        with self._lock:
            watches = sorted(self._watches.values(), key=lambda w: w.wid)
            return [w.describe() for w in watches]

    @property
    def n_active(self) -> int:
        with self._lock:
            return len(self._watches)

    def _update_active_gauge(self) -> None:
        if self._g_active is None:
            return
        with self._lock:
            by_kind = dict(self._active_by_kind)
        for k in WATCH_KINDS:
            self._g_active.set(float(by_kind.get(k, 0)), kind=k)

    # -- flush-worker hooks (non-blocking by contract) ------------------------
    def offer(self, state, table, set_shift: int, ts: int,
              hist_seq: Optional[int] = None) -> None:
        """Hand one DETACHED interval to the engine thread. Called by
        server._do_flush after compute_flush (which does not donate, so
        the state reference stays valid for this thread's launch).
        `hist_seq` is the history-ring window seq this interval landed
        in (the flush wrote it before offering), pinned HERE because a
        later flush may advance the ring before the engine thread
        evaluates; None when the history tier is off."""
        if self._stop.is_set():
            return
        with self._lock:
            if not self._watches:
                return
        job = (state, table, int(set_shift), int(ts), hist_seq)
        try:
            self._jobs.put_nowait(job)
        except queue_mod.Full:  # vtlint: disable=accounting-flow -- the unaccounted branch is a raced-empty queue followed by a successful re-put: nothing was lost on it
            # drop the OLDEST queued interval — the newest state is the
            # one standing monitors want — and account every active
            # watch's lost evaluation as suppressed (exact: one per
            # watch per skipped interval)
            try:
                stale = self._jobs.get_nowait()
            except queue_mod.Empty:
                stale = None
            if stale is not None:
                self.intervals_skipped += 1
                self._count_skipped_interval()
            try:
                self._jobs.put_nowait(job)
            except queue_mod.Full:
                # engine wedged mid-drain and the queue refilled: THIS
                # interval is the one skipped, same exact accounting
                self.intervals_skipped += 1
                self._count_skipped_interval()

    def skip_interval(self, reason: str) -> None:
        """Overload-CRITICAL (or failure) skip: the flush worker sheds
        watch evaluation instead of offering the interval. Counted —
        one suppressed per active watch — never silent."""
        with self._lock:
            if not self._watches:
                return
        self.intervals_skipped += 1
        self._count_skipped_interval()
        log.debug("watch evaluation skipped for one interval: %s", reason)

    def _count_skipped_interval(self) -> None:
        if self._c_suppressed is None:
            return
        with self._lock:
            by_kind = {k: n for k, n in self._active_by_kind.items() if n}
        for k, n in by_kind.items():
            self._c_suppressed.inc(n, kind=k)

    # -- engine thread -------------------------------------------------------
    def _run(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                if self._stop.is_set():
                    return
                continue
            state, table, set_shift, ts, hist_seq = job
            try:
                self._evaluate_interval(state, table, set_shift, ts,
                                        hist_seq)
            except Exception:  # noqa: BLE001 — the engine must survive
                log.exception("watch evaluation failed; interval counted "
                              "as skipped")
                self.intervals_skipped += 1
                self._count_skipped_interval()
            if self._stop.is_set():
                return

    def _index_and_plan(self, table, watches):
        """Selector→row resolution against the detached table's sorted
        NameIndex. swap() installs a fresh KeyTable per interval, so
        the cache key (table identity, per-kind counts, watch-set
        generation) re-resolves exactly when the naming view or the
        watch set changed — table growth and reshard included."""
        metas = {t: table.get_meta(_META_KIND[t]) for t in COUNT_TABLES}
        counts = {t: len(metas[t]) for t in COUNT_TABLES}
        with self._lock:
            gen = self._generation
        key = (id(table), tuple(counts[t] for t in COUNT_TABLES), gen)
        if self._plan_key == key and self._plan_table is table:
            return self._plan
        index = NameIndex(metas, counts)
        plan = compile_watches(self.spec, index, watches)
        self._plan, self._plan_key, self._plan_table = plan, key, table
        return plan

    def _launch(self, state, plan: WatchPlan):
        """The watch tier's ONE device dispatch per interval (vtlint
        jax-hot-path + timer-sync covered): enqueue cost lands in
        dispatch_ns; the sampled completion sync runs in _materialize
        on this same engine thread."""
        from veneur_tpu.aggregation.step import flush_live_in_packed
        flat = self._server.aggregator.query_flat_state(state)
        t0 = time.perf_counter_ns()
        out = flush_live_in_packed(flat, plan.inputs, spec=self.spec,
                                   n_q=plan.n_q, buckets=plan.buckets)
        self.dispatch_ns += time.perf_counter_ns() - t0
        self.launches_total += 1
        return out

    def _materialize(self, packed, plan: WatchPlan, set_shift: int):
        from veneur_tpu.aggregation.step import (combine_flush_scalars,
                                                 flush_live_shapes,
                                                 unpack_flush)
        self._sync.tick(packed)
        out = unpack_flush(
            np.asarray(packed),
            flush_live_shapes(self.spec, *plan.buckets, plan.n_q))
        res = combine_flush_scalars(out)
        # detached-interval set estimates carry the degrade ladder's
        # latched sampling shift — the same 2^shift correction
        # server._do_flush applies to the flush export
        if set_shift:
            res = dict(res)
            res["set_estimate"] = (res["set_estimate"]
                                   * float(1 << set_shift))
        return res

    def _value_for(self, w: Watch, plan: Optional[WatchPlan],
                   res) -> Optional[float]:
        if plan is None or res is None:
            return None
        vals: List[float] = []
        for tname, r in plan.rows.get(w.wid, ()):
            if tname == "counter":
                v = res["counter"][r]
            elif tname == "gauge":
                v = res["gauge"][r]
            elif tname == "status":
                v = res["status"][r]
            elif tname == "set":
                v = res["set_estimate"][r]
            else:
                v = res["histo_quantiles"][r,
                                           plan.qcol[float(w.quantile)]]
            v = float(v)
            if math.isfinite(v):
                vals.append(v)
        return w.reduce(vals)

    def _delta_baselines(self, watches,
                         hist_seq: Optional[int]) -> Optional[dict]:
        """Previous-interval baselines for delta watches, read from the
        HISTORY RING in one batched device gather: {wid: value | None}.
        None (no dict) when the tier is off / unarmed / there is no
        previous window — callers then fall back to the watch's own
        retained last_value (the pre-history behavior)."""
        if (self._history is None or hist_seq is None or hist_seq < 1
                or not self._history.armed):
            return None
        deltas = [w for w in watches if w.kind == "delta"]
        if not deltas:
            return None
        from fnmatch import fnmatchcase
        keys = self._history.iter_keys()
        items: List[tuple] = []
        slots: Dict[int, List[int]] = {}
        for w in deltas:
            allowed = w.metric_kinds or ("counter", "gauge", "status")
            tags_j = ",".join(w.tags) if w.tags is not None else None
            matched = []
            for k, key, row in keys:
                kind, name, jt = key
                if k > 2 or kind not in allowed:
                    continue
                if tags_j is not None and jt != tags_j:
                    continue
                if w.mode == "name":
                    ok = name == w.arg
                elif w.mode == "prefix":
                    ok = name.startswith(w.arg)
                else:
                    ok = fnmatchcase(name, w.arg)
                if ok:
                    matched.append(len(items))
                    items.append((k, row))
            slots[w.wid] = matched
        out: Dict[int, Optional[float]] = {}
        vals = self._history.read_values(hist_seq - 1, items)
        for w in deltas:
            vs = [float(vals[i]) for i in slots[w.wid]
                  if math.isfinite(vals[i])]
            out[w.wid] = w.reduce(vs)
        return out

    def _evaluate_interval(self, state, table, set_shift: int,
                           ts: int, hist_seq: Optional[int] = None
                           ) -> None:
        t0 = time.perf_counter_ns()
        with self._lock:
            watches = sorted(self._watches.values(), key=lambda w: w.wid)
        if not watches:
            return
        plan = self._index_and_plan(table, watches)
        res = None
        if plan is not None:
            packed = self._launch(state, plan)
            res = self._materialize(packed, plan, set_shift)
        # delta lookback: the ring window written by the PREVIOUS flush
        # is the baseline of record when the history tier is on
        baselines = self._delta_baselines(watches, hist_seq)
        stale = bool(getattr(self._server, "reshard_active", False))
        events: List[dict] = []
        n_eval: Dict[str, int] = {}
        n_fired: Dict[str, int] = {}
        n_supp: Dict[str, int] = {}
        with self._lock:
            for w in watches:
                if self._watches.get(w.wid) is not w:
                    continue   # deleted (or replaced) mid-interval
                value = self._value_for(w, plan, res)
                if w.kind == "delta" and baselines is not None:
                    transition, suppressed = w.observe(
                        value, ts, prev_override=baselines.get(w.wid))
                else:
                    transition, suppressed = w.observe(value, ts)
                n_eval[w.kind] = n_eval.get(w.kind, 0) + 1
                if suppressed:
                    n_supp[w.kind] = n_supp.get(w.kind, 0) + 1
                if transition is None:
                    continue
                old, new = transition
                if new == "ALERT":
                    n_fired[w.kind] = n_fired.get(w.kind, 0) + 1
                ev = {"id": w.wid, "kind": w.kind, w.mode: w.arg,
                      "from": old, "to": new, "ts": int(ts),
                      "threshold": w.threshold}
                if w.value is not None:
                    ev["value"] = w.value
                if stale:
                    ev["stale_bounded"] = True
                events.append(ev)
        for k, n in n_eval.items():
            if self._c_evaluated is not None:
                self._c_evaluated.inc(n, kind=k)
        for k, n in n_fired.items():
            if self._c_fired is not None:
                self._c_fired.inc(n, kind=k)
        for k, n in n_supp.items():
            if self._c_suppressed is not None:
                self._c_suppressed.inc(n, kind=k)
        self.intervals_evaluated += 1
        if events:
            self.hub.publish(events)
            if self.webhook is not None:
                self.webhook.post_events(events)
        # vtlint: disable=timer-sync -- _materialize's np.asarray host-materialized the packed result (implicit sync) before this timestamp; the launch-only enqueue cost is tracked separately as dispatch_ns
        dur = time.perf_counter_ns() - t0
        if self._c_eval_ns is not None:
            self._c_eval_ns.inc(dur)

    # -- persistence ---------------------------------------------------------
    def snapshot(self) -> Optional[dict]:
        """Deterministic registration + firing-state dict for the
        checkpoint sidecar chunk. None (chunk omitted) when no watches
        are registered. Byte-reproducible: snapshot → restore →
        snapshot serializes identically."""
        with self._lock:
            if not self._watches:
                return None
            return {"next_id": self._next_id,
                    "watches": [{"spec": w.to_dict(),
                                 "state": w.state_dict()}
                                for _wid, w in sorted(
                                    self._watches.items())]}

    def restore(self, data: dict) -> None:
        """Adopt a checkpoint's watch chunk (replacing any current
        registrations — restore runs before the HTTP API serves). A
        malformed chunk is logged and ignored: a bad checkpoint must
        never keep the server from serving."""
        try:
            ws: Dict[int, Watch] = {}
            for ent in data.get("watches", []):
                spec = dict(ent["spec"])
                wid = int(spec.pop("id"))
                w = Watch(wid, parse_watch(spec))
                w.load_state(ent.get("state") or {})
                ws[wid] = w
            next_id = max([int(data.get("next_id", 1))]
                          + [wid + 1 for wid in ws])
        except (WatchError, KeyError, TypeError, ValueError) as e:
            log.warning("ignoring malformed watch chunk in checkpoint: "
                        "%s", e)
            return
        by_kind: Dict[str, int] = {}
        for w in ws.values():
            by_kind[w.kind] = by_kind.get(w.kind, 0) + 1
        with self._lock:
            self._watches = ws
            self._active_by_kind = by_kind
            self._next_id = next_id
            self._generation += 1
        self._update_active_gauge()
        log.info("restored %d watches from checkpoint", len(ws))

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        """Stop the engine thread before JAX teardown (it launches on
        the device). Queued intervals that never ran are counted."""
        self._stop.set()
        while True:
            try:
                self._jobs.put_nowait(None)
                break
            except queue_mod.Full:  # vtlint: disable=accounting-flow -- unaccounted branches retry the sentinel put or displace a prior sentinel; no interval data is lost on them
                # displace a queued interval to make room for the
                # sentinel; its lost evaluations are accounted like any
                # other skipped interval
                try:
                    stale = self._jobs.get_nowait()
                except queue_mod.Empty:
                    continue
                if stale is not None:
                    self.intervals_skipped += 1
                    self._count_skipped_interval()
        self._thread.join(timeout=_CLOSE_TIMEOUT_S)
        if self._thread.is_alive():
            log.error("watch engine thread did not exit within %.0fs",
                      _CLOSE_TIMEOUT_S)
        # the thread exits on the first job it sees after _stop, which
        # can strand later queued intervals — account them too
        while True:
            try:
                job = self._jobs.get_nowait()
            except queue_mod.Empty:
                break
            if job is not None:
                self.intervals_skipped += 1
                self._count_skipped_interval()
