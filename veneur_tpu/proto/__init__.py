"""Generated protobuf bindings (wire-compatible with the reference's SSF /
metricpb / forwardrpc schemas; regenerate with scripts in Makefile)."""
from veneur_tpu.proto import ssf_pb2, tdigestpb_pb2, metricpb_pb2, forwardrpc_pb2  # noqa: F401
