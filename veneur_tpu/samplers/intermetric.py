"""InterMetric — the post-aggregation metric record handed to sinks.

Mirrors reference samplers/samplers.go:48-127: InterMetric{Name, Timestamp,
Value, Tags, Type, Message, HostName, Sinks}, metric types counter/gauge/
status, and the `veneursinkonly:<name>` routing tag semantics
(RouteInformation, samplers.go:33-44, 110-127).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

COUNTER = "counter"
GAUGE = "gauge"
STATUS = "status"

SINK_ONLY_TAG_PREFIX = "veneursinkonly:"


@dataclasses.dataclass(slots=True)
class InterMetric:
    name: str
    timestamp: int
    value: float
    tags: List[str]
    type: str
    message: str = ""
    hostname: str = ""
    sinks: Optional[frozenset] = None  # None = route to every sink

    def is_acceptable_to(self, sink_name: str) -> bool:
        """reference sinks/sinks.go:51 IsAcceptableMetric."""
        return self.sinks is None or sink_name in self.sinks


def route_info(tags) -> Optional[frozenset]:
    """Extract `veneursinkonly:` destinations from a tag list
    (reference samplers/samplers.go:110-127 routeInfo)."""
    dests = frozenset(t[len(SINK_ONLY_TAG_PREFIX):] for t in tags
                      if t.startswith(SINK_ONLY_TAG_PREFIX))
    return dests or None
