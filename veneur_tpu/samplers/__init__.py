"""Parsing and metric keying.

The device-side "samplers" themselves live in aggregation/ (the key table);
this package holds the wire-facing parse layer: DogStatsD datagrams, events,
service checks, and SSF sample conversion, with semantics matching the
reference's samplers/parser.go so existing emitters work unchanged.
"""

from veneur_tpu.samplers.parser import (
    MIXED_SCOPE, LOCAL_ONLY, GLOBAL_ONLY,
    UDPMetric, parse_metric, parse_event, parse_service_check,
    parse_metric_ssf, parse_tags_to_map, ParseError)

__all__ = [
    "MIXED_SCOPE", "LOCAL_ONLY", "GLOBAL_ONLY", "UDPMetric", "parse_metric",
    "parse_event", "parse_service_check", "parse_metric_ssf",
    "parse_tags_to_map", "ParseError",
]
