"""DogStatsD / SSF parsing with reference-identical semantics.

Behavioral contract mirrors the reference's samplers/parser.go:
- ParseMetric (parser.go:298): ``name:value|type[|@rate][|#tags]``; type
  bytes c/g/d/h/ms/s; strict malformed-packet rejection; each section at
  most once; sample rate in (0, 1]; tags sorted then joined with ","; the
  32-bit FNV-1a digest over name+type+joined-tags is the sharding key.
- Magic tags (parser.go:397-407): the FIRST sorted tag with prefix
  "veneurlocalonly"/"veneurglobalonly" is stripped and becomes the scope
  (note: prefix match, first match only — both present means the
  lexicographically-earlier "veneurglobalonly" wins and the local tag
  remains in the tag list; we reproduce that).
- ParseEvent (parser.go:431): ``_e{tl,tx}:title|text|...`` with d:/h:/k:/
  p:/s:/t:/#tags metadata, producing an SSF sample carrying the
  vdogstatsd_* conduit tags.
- ParseServiceCheck (parser.go:579): ``_sc|name|status|...`` with d:/h:/
  #tags/m: (message must be last); digest stays 0 (the reference never
  digests service checks — they all land on worker 0, server.go:973).
- ParseMetricSSF (parser.go:239): SSFSample -> UDPMetric, where the
  sample's map tags become sorted "k:v" strings and zero sample rates were
  already normalized to 1 by the wire layer.

The value of keeping these semantics bit-exact is shard compatibility: a
mixed fleet of reference instances and this framework hashes every key to
the same digest, so proxies can route to either.
"""

from __future__ import annotations

import copy
import dataclasses
import random
import struct
import time
from typing import Optional, Union

from veneur_tpu.proto import ssf_pb2
from veneur_tpu.protocol.wire import valid_trace
from veneur_tpu.samplers import ssf_samples
from veneur_tpu.utils.hashing import FNV32_OFFSET, FNV32_PRIME

# MetricScope (reference parser.go:66-70)
MIXED_SCOPE = 0
LOCAL_ONLY = 1
GLOBAL_ONLY = 2

# DogStatsD event conduit tags (reference protocol/dogstatsd/protocol.go)
EVENT_IDENTIFIER_KEY = "vdogstatsd_ev"
EVENT_AGGREGATION_KEY_TAG_KEY = "vdogstatsd_ak"
EVENT_ALERT_TYPE_TAG_KEY = "vdogstatsd_at"
EVENT_HOSTNAME_TAG_KEY = "vdogstatsd_hostname"
EVENT_PRIORITY_TAG_KEY = "vdogstatsd_pri"
EVENT_SOURCE_TYPE_TAG_KEY = "vdogstatsd_st"


class ParseError(ValueError):
    pass


@dataclasses.dataclass
class UDPMetric:
    """A parsed sample; the MetricKey is (name, type, joined_tags)."""
    name: str = ""
    type: str = ""
    value: Union[float, str, int, None] = None
    digest: int = 0
    sample_rate: float = 1.0
    tags: tuple = ()
    joined_tags: str = ""
    scope: int = MIXED_SCOPE
    timestamp: int = 0
    message: str = ""
    hostname: str = ""

    def key(self):
        return (self.name, self.type, self.joined_tags)


def _fnv_add(h: int, data: bytes) -> int:
    for b in data:
        h = ((h ^ b) * FNV32_PRIME) & 0xFFFFFFFF
    return h


_TYPE_BY_BYTE = {
    ord("c"): "counter",
    ord("g"): "gauge",
    ord("d"): "histogram",  # DogStatsD "distribution" -> histogram
    ord("h"): "histogram",
    ord("m"): "timer",      # "ms"; trailing 's' ignored
    ord("s"): "set",
}


def _strip_magic_tags(tags: list) -> tuple:
    """Sorted-first-prefix-match magic tag stripping; returns (tags, scope)."""
    scope = MIXED_SCOPE
    for i, tag in enumerate(tags):
        if tag.startswith("veneurlocalonly"):
            del tags[i]
            scope = LOCAL_ONLY
            break
        if tag.startswith("veneurglobalonly"):
            del tags[i]
            scope = GLOBAL_ONLY
            break
    return tags, scope


# Key-level parse cache: digest (3 sequential per-byte FNV passes — the
# dominant pure-Python cost), decoded name, sorted/joined tags and scope
# depend only on (name bytes, type, raw tag section), which a steady-state
# server sees over and over (the reference pays the same work per sample
# in Go, worker.go:344; the C++ engine caches nothing because its FNV is
# ~free). Bounded: cleared wholesale when full, so a cardinality attack
# costs a re-warm, not memory.
_KEY_CACHE: dict = {}
_KEY_CACHE_MAX = 1 << 16
# same idea for the SSF converter (parse_metric_ssf): digest keyed by
# (name, type, joined_tags), bounded by wholesale clear
_SSF_DIGEST_CACHE: dict = {}


def _cache_put(cache: dict, key, value):
    """The shared bounded-insert idiom: wholesale clear when full, so a
    cardinality attack costs a re-warm, not memory."""
    if len(cache) >= _KEY_CACHE_MAX:
        cache.clear()
    cache[key] = value


def _pb_str(b: bytes) -> str:
    """Decode bytes destined for an SSF protobuf STRING field. Protobuf
    rejects surrogates (assignment raises, which killed the pipeline
    thread for one corrupt event datagram — the set-member DoS class),
    so invalid UTF-8 becomes U+FFFD here — the same replacement Go's
    encoding/json applies to invalid bytes when the reference marshals
    events downstream. Metric-path decodes keep surrogateescape — key
    identity must round-trip to the original bytes — and the forward
    path applies the same replacement at ITS protobuf boundary
    (forward/convert.py _wire_str)."""
    return b.decode("utf-8", "replace")


def _f32(x: float) -> float:
    """Round-trip through float32 — SSFSample.value/sample_rate are proto
    `float` fields, so every cold-path metric is f32-quantized; hot
    template paths must quantize identically or warm keys would emit
    different bits than cold keys for the same span."""
    return struct.unpack("f", struct.pack("f", x))[0]


def _key_info(name_b: bytes, mtype: str, tags_chunk):
    ck = (name_b, mtype, tags_chunk)
    info = _KEY_CACHE.get(ck)
    if info is None:
        h = _fnv_add(FNV32_OFFSET, name_b)
        h = _fnv_add(h, mtype.encode())
        if tags_chunk is None:
            tags, joined, scope = (), "", MIXED_SCOPE
        else:
            tl = sorted(
                tags_chunk[1:].decode("utf-8", "surrogateescape")
                .split(","))
            tl, scope = _strip_magic_tags(tl)
            tags = tuple(tl)
            joined = ",".join(tl)
            h = _fnv_add(h, joined.encode("utf-8", "surrogateescape"))
        info = (h, name_b.decode("utf-8", "surrogateescape"), tags,
                joined, scope)
        _cache_put(_KEY_CACHE, ck, info)
    return info


def parse_metric(packet: bytes) -> UDPMetric:
    """Parse one DogStatsD datagram line into a UDPMetric."""
    chunks = packet.split(b"|")
    first = chunks[0]
    colon = first.find(b":")
    if colon == -1:
        raise ParseError("need at least 1 colon")
    name_b = first[:colon]
    value_b = first[colon + 1:]
    if not name_b:
        raise ParseError("name cannot be empty")
    if len(chunks) < 2:
        raise ParseError("need at least 1 pipe for type")
    type_b = chunks[1]
    if not type_b:
        raise ParseError("metric type not specified")

    mtype = _TYPE_BY_BYTE.get(type_b[0])
    if mtype is None:
        raise ParseError("invalid type for metric")

    m = UDPMetric(type=mtype)

    if mtype == "set":
        m.value = value_b.decode("utf-8", "surrogateescape")
    else:
        # Go's strconv.ParseFloat is stricter than Python float(): no
        # surrounding whitespace, no underscores.
        if value_b != value_b.strip() or b"_" in value_b:
            raise ParseError("invalid number for metric value")
        try:
            v = float(value_b)
        except ValueError:
            raise ParseError("invalid number for metric value")
        if v != v or v in (float("inf"), float("-inf")):
            raise ParseError("invalid number for metric value")
        m.value = v

    found_rate = False
    tags_chunk = None
    for chunk in chunks[2:]:
        if not chunk:
            raise ParseError("empty string after/between pipes")
        lead = chunk[0]
        if lead == 0x40:  # '@'
            if found_rate:
                raise ParseError("multiple sample rates specified")
            rate_b = chunk[1:]
            # same strictness as the value: no underscores (Python float
            # accepts '0.2_5', the wire format does not) and finite — a
            # NaN rate would pass the range checks below (NaN comparisons
            # are false) and poison counters with value*(1/NaN)
            if b"_" in rate_b or rate_b != rate_b.strip():
                raise ParseError("invalid float for sample rate")
            try:
                rate = float(rate_b)
            except ValueError:
                raise ParseError("invalid float for sample rate")
            if rate != rate or not (0 < rate <= 1):
                raise ParseError("sample rate must be >0 and <=1")
            m.sample_rate = rate
            found_rate = True
        elif lead == 0x23:  # '#'
            if tags_chunk is not None:
                raise ParseError("multiple tag sections specified")
            tags_chunk = chunk
        else:
            raise ParseError("contains unknown section")

    m.digest, m.name, m.tags, m.joined_tags, m.scope = _key_info(
        name_b, mtype, tags_chunk)
    return m


def parse_tags_to_map(tags) -> dict:
    """Split "k:v" tag strings into a dict (reference parser.go:696)."""
    out = {}
    for tag in tags:
        k, _, v = tag.partition(":")
        out[k] = v
    return out


def parse_event(packet: bytes, now: Optional[int] = None) -> ssf_pb2.SSFSample:
    """Parse a DogStatsD event into an SSF sample with vdogstatsd_* tags."""
    chunks = packet.split(b"|")
    first = chunks[0]
    colon = first.find(b":")
    if colon == -1:
        raise ParseError("event needs at least 1 colon")
    lengths = first[:colon]
    if not lengths.startswith(b"_e{") or not lengths.endswith(b"}"):
        raise ParseError("event must have _e{} wrapper around length section")
    lengths = lengths[3:-1]
    comma = lengths.find(b",")
    if comma == -1:
        raise ParseError("event length section requires comma divider")
    try:
        title_len = int(lengths[:comma])
        text_len = int(lengths[comma + 1:])
    except ValueError:
        raise ParseError("event lengths must be integers")
    if title_len <= 0 or text_len <= 0:
        raise ParseError("event lengths must be positive")

    title = first[colon + 1:]
    if len(title) != title_len:
        raise ParseError("actual title length did not match encoded length")
    if len(chunks) < 2:
        raise ParseError("event must have at least 1 pipe for text")
    text = chunks[1]
    if len(text) != text_len:
        raise ParseError("actual text length did not match encoded length")

    sample = ssf_pb2.SSFSample(
        name=_pb_str(title),
        message=_pb_str(text).replace("\\n", "\n"),
        timestamp=now if now is not None else int(time.time()),
    )
    sample.tags[EVENT_IDENTIFIER_KEY] = ""

    seen = set()

    def once(key):
        if key in seen:
            raise ParseError(f"multiple {key} sections")
        seen.add(key)

    for chunk in chunks[2:]:
        if not chunk:
            raise ParseError("empty string after/between pipes")
        if chunk.startswith(b"d:"):
            once("date")
            try:
                sample.timestamp = int(chunk[2:])
            except ValueError:
                raise ParseError("could not parse date as unix timestamp")
        elif chunk.startswith(b"h:"):
            once("hostname")
            sample.tags[EVENT_HOSTNAME_TAG_KEY] = _pb_str(chunk[2:])
        elif chunk.startswith(b"k:"):
            once("aggregation")
            sample.tags[EVENT_AGGREGATION_KEY_TAG_KEY] = _pb_str(chunk[2:])
        elif chunk.startswith(b"p:"):
            once("priority")
            pri = _pb_str(chunk[2:])
            if pri not in ("normal", "low"):
                raise ParseError("priority must be normal or low")
            sample.tags[EVENT_PRIORITY_TAG_KEY] = pri
        elif chunk.startswith(b"s:"):
            once("source")
            sample.tags[EVENT_SOURCE_TYPE_TAG_KEY] = _pb_str(chunk[2:])
        elif chunk.startswith(b"t:"):
            once("alert")
            alert = _pb_str(chunk[2:])
            if alert not in ("error", "warning", "info", "success"):
                raise ParseError(
                    "alert level must be error, warning, info or success")
            sample.tags[EVENT_ALERT_TYPE_TAG_KEY] = alert
        elif chunk[0] == 0x23:  # '#'
            once("tags")
            tags = _pb_str(chunk[1:]).split(",")
            for k, v in parse_tags_to_map(tags).items():
                sample.tags[k] = v
        else:
            raise ParseError("unrecognized event metadata section")
    return sample


def parse_service_check(packet: bytes, now: Optional[int] = None) -> UDPMetric:
    """Parse a DogStatsD service check into a status-typed UDPMetric."""
    chunks = packet.split(b"|")
    if chunks[0] != b"_sc":
        raise ParseError("service check needs _sc prefix")
    if len(chunks) < 2:
        raise ParseError("service check needs name section")
    if not chunks[1]:
        raise ParseError("service check name cannot be empty")
    if len(chunks) < 3:
        raise ParseError("service check needs status section")

    status_map = {b"0": ssf_pb2.SSFSample.OK, b"1": ssf_pb2.SSFSample.WARNING,
                  b"2": ssf_pb2.SSFSample.CRITICAL,
                  b"3": ssf_pb2.SSFSample.UNKNOWN}
    if chunks[2] not in status_map:
        raise ParseError("service check status must be 0, 1, 2, or 3")

    m = UDPMetric(
        type="status",
        name=chunks[1].decode("utf-8", "surrogateescape"),
        value=int(status_map[chunks[2]]),
        timestamp=now if now is not None else int(time.time()),
    )

    found = set()
    found_message = False
    for chunk in chunks[3:]:
        if not chunk:
            raise ParseError("empty string after/between pipes")
        if found_message:
            raise ParseError("message must be the last metadata section")
        if chunk.startswith(b"d:"):
            if "date" in found:
                raise ParseError("multiple date sections")
            found.add("date")
            try:
                m.timestamp = int(chunk[2:])
            except ValueError:
                raise ParseError("could not parse date as unix timestamp")
        elif chunk.startswith(b"h:"):
            if "hostname" in found:
                raise ParseError("multiple hostname sections")
            found.add("hostname")
            m.hostname = chunk[2:].decode("utf-8", "surrogateescape")
        elif chunk.startswith(b"m:"):
            m.message = chunk[2:].decode(
                "utf-8", "surrogateescape").replace("\\n", "\n")
            found_message = True
        elif chunk[0] == 0x23:  # '#'
            if "tags" in found:
                raise ParseError("multiple tag sections")
            found.add("tags")
            tags = sorted(chunk[1:].decode("utf-8", "surrogateescape").split(","))
            # exact-equality magic tags here (unlike metric prefix match)
            scope = MIXED_SCOPE
            for i, tag in enumerate(tags):
                if tag == "veneurlocalonly":
                    del tags[i]
                    scope = LOCAL_ONLY
                    break
                if tag == "veneurglobalonly":
                    del tags[i]
                    scope = GLOBAL_ONLY
                    break
            m.scope = scope
            m.tags = tuple(tags)
            m.joined_tags = ",".join(tags)
        else:
            raise ParseError("unrecognized service check metadata section")
    return m


_SSF_TYPE = {
    ssf_pb2.SSFSample.COUNTER: "counter",
    ssf_pb2.SSFSample.GAUGE: "gauge",
    ssf_pb2.SSFSample.HISTOGRAM: "histogram",
    ssf_pb2.SSFSample.SET: "set",
    ssf_pb2.SSFSample.STATUS: "status",
}


def parse_metric_ssf(sample: ssf_pb2.SSFSample) -> UDPMetric:
    """Convert an SSF sample to a UDPMetric (reference parser.go:239)."""
    mtype = _SSF_TYPE.get(sample.metric)
    if mtype is None:
        raise ParseError("invalid type for metric")
    m = UDPMetric(type=mtype, name=sample.name)

    if sample.metric == ssf_pb2.SSFSample.SET:
        m.value = sample.message
    elif sample.metric == ssf_pb2.SSFSample.STATUS:
        m.value = int(sample.status)
    else:
        m.value = float(sample.value)

    if sample.scope == ssf_pb2.SSFSample.LOCAL:
        m.scope = LOCAL_ONLY
    elif sample.scope == ssf_pb2.SSFSample.GLOBAL:
        m.scope = GLOBAL_ONLY

    m.sample_rate = sample.sample_rate
    tags = []
    for k, v in sample.tags.items():
        if k == "veneurlocalonly":
            m.scope = LOCAL_ONLY
            continue
        if k == "veneurglobalonly":
            m.scope = GLOBAL_ONLY
            continue
        tags.append(f"{k}:{v}")
    tags.sort()
    m.tags = tuple(tags)
    m.joined_tags = ",".join(tags)
    # the three sequential per-byte FNV passes dominate this converter's
    # pure-Python cost (the dogstatsd text path caches the same way,
    # _key_info above); extraction workloads repeat (name, type, tags)
    # shapes heavily — SLI timers vary only by service/error tags
    ck = (m.name, mtype, m.joined_tags)
    h = _SSF_DIGEST_CACHE.get(ck)
    if h is None:
        h = _fnv_add(FNV32_OFFSET,
                     m.name.encode("utf-8", "surrogateescape"))
        h = _fnv_add(h, mtype.encode())
        h = _fnv_add(h, m.joined_tags.encode("utf-8", "surrogateescape"))
        _cache_put(_SSF_DIGEST_CACHE, ck, h)
    m.digest = h
    return m


def valid_metric(m: UDPMetric) -> bool:
    """reference parser.go ValidMetric."""
    return bool(m.name) and m.value is not None


def convert_metrics(span):
    """Extract the span's embedded SSF samples as UDPMetrics (reference
    parser.go:103 ConvertMetrics). Returns (metrics, invalid_samples)."""
    metrics, invalid = [], []
    for sample in span.metrics:
        try:
            m = parse_metric_ssf(sample)
        except ParseError:
            invalid.append(sample)
            continue
        if not valid_metric(m):
            invalid.append(sample)
            continue
        metrics.append(m)
    return metrics, invalid


def _clone_metric(tpl: UDPMetric) -> UDPMetric:
    """Shallow template clone. copy.copy routes through __reduce_ex__
    (~8x the cost); every UDPMetric field is an immutable scalar/tuple,
    so a __dict__ copy is safe and this sits on the span-firehose hot
    path."""
    m = object.__new__(UDPMetric)
    m.__dict__.update(tpl.__dict__)
    return m


_INDICATOR_TPL_CACHE: dict = {}


def convert_indicator_metrics(span, indicator_timer_name: str,
                              objective_timer_name: str):
    """Indicator spans -> SLI timers (reference parser.go:129
    ConvertIndicatorMetrics): duration as an indicator timer tagged
    service/error, and an objective timer additionally tagged with the
    span name (overridable via the ssf_objective tag) and
    veneurglobalonly.

    Everything except the duration is a pure function of
    (service, error, objective) — tiny cardinality on a real span
    firehose — so the built UDPMetrics are cached as templates and
    cloned per span; the SSFSample-protobuf + parse path runs only on a
    cold key (measured ~5x on the extraction hot loop, which is the
    host floor of BASELINE config 5's span firehose)."""
    if not span.indicator or not valid_trace(span):
        return []
    duration_s = (span.end_timestamp - span.start_timestamp) / 1e9
    err = "true" if span.error else "false"
    objective = (span.tags.get("ssf_objective") or span.name) \
        if objective_timer_name else ""
    ck = (indicator_timer_name, objective_timer_name, span.service, err,
          objective)
    tpls = _INDICATOR_TPL_CACHE.get(ck)
    if tpls is None:
        out = []
        if indicator_timer_name:
            t = ssf_samples.timing(indicator_timer_name, duration_s,
                                   {"service": span.service, "error": err})
            out.append(parse_metric_ssf(t))
        if objective_timer_name:
            t = ssf_samples.timing(objective_timer_name, duration_s,
                                   {"service": span.service,
                                    "objective": objective,
                                    "error": err,
                                    "veneurglobalonly": "true"})
            out.append(parse_metric_ssf(t))
        # cache COPIES: the returned metrics must never alias templates
        _cache_put(_INDICATOR_TPL_CACHE, ck,
                   tuple(copy.copy(m) for m in out))
        return out
    # same arithmetic as the cold path, INCLUDING the f32 quantization
    # the SSFSample proto value field imposes, so hot and cold spans
    # are bit-identical
    value = _f32(duration_s * 1e9)
    out = []
    for tpl in tpls:
        m = _clone_metric(tpl)
        m.value = value
        out.append(m)
    return out


_UNIQUENESS_TPL_CACHE: dict = {}


def convert_span_uniqueness_metrics(span, rate: float = 0.01):
    """Unique span-name Sets per service at a sampling rate (reference
    parser.go:187 ConvertSpanUniquenessMetrics).

    The sampling roll runs FIRST (same Bernoulli semantics as
    RandomlySample, samples.go:128) so the 99% of spans that sample out
    never pay the protobuf construction, and kept samples clone a cached
    template keyed by the span's tag shape — only the set member (the
    span name) and the effective sample rate vary."""
    if not span.service:
        return []
    if rate < 1.0 and random.random() >= rate:
        return []
    ck = (span.service, bool(span.indicator), span.id == span.trace_id)
    tpl = _UNIQUENESS_TPL_CACHE.get(ck)
    if tpl is None:
        s = ssf_samples.set_("ssf.names_unique", span.name, {
            "indicator": "true" if span.indicator else "false",
            "service": span.service,
            "root_span": "true" if span.id == span.trace_id else "false",
        })
        if rate < 1.0:
            s.sample_rate = rate      # RandomlySample's marking
        m = parse_metric_ssf(s)
        # cache a COPY: the returned metric must never alias the template
        _cache_put(_UNIQUENESS_TPL_CACHE, ck, copy.copy(m))
        return [m]
    m = _clone_metric(tpl)
    m.value = span.name
    # f32 like the cold path's proto sample_rate field
    m.sample_rate = _f32(rate) if rate < 1.0 else 1.0
    return [m]
