"""SSFSample constructor helpers (reference ssf/samples.go:
Count/Gauge/Histogram/Timing/Set/Status + RandomlySample)."""

from __future__ import annotations

import random
import time
from typing import Dict, List, Optional

from veneur_tpu.proto import ssf_pb2


def _mk(metric, name, value, tags, unit="", message="", status=None,
        timestamp=None):
    s = ssf_pb2.SSFSample(
        metric=metric, name=name, value=float(value),
        timestamp=int(timestamp if timestamp is not None
                      else time.time() * 1e9),
        sample_rate=1.0, unit=unit, message=message)
    if status is not None:
        s.status = status
    if tags:
        for k, v in tags.items():
            s.tags[k] = v
    return s


def count(name: str, value: float, tags: Optional[Dict] = None, **kw):
    return _mk(ssf_pb2.SSFSample.COUNTER, name, value, tags, **kw)


def gauge(name: str, value: float, tags: Optional[Dict] = None, **kw):
    return _mk(ssf_pb2.SSFSample.GAUGE, name, value, tags, **kw)


def histogram(name: str, value: float, tags: Optional[Dict] = None, **kw):
    return _mk(ssf_pb2.SSFSample.HISTOGRAM, name, value, tags, **kw)


def timing(name: str, duration_s: float, tags: Optional[Dict] = None, **kw):
    """Duration as a nanosecond-resolution timer (samples.go:209 Timing with
    time.Nanosecond resolution)."""
    return _mk(ssf_pb2.SSFSample.HISTOGRAM, name, duration_s * 1e9, tags,
               unit="ns", **kw)


def set_(name: str, value: str, tags: Optional[Dict] = None, **kw):
    s = _mk(ssf_pb2.SSFSample.SET, name, 0.0, tags, **kw)
    s.message = value  # set member rides the message field (samples.go:197)
    return s


def status(name: str, state: int, tags: Optional[Dict] = None,
           message: str = "", **kw):
    return _mk(ssf_pb2.SSFSample.STATUS, name, float(state), tags,
               message=message, **kw)


def randomly_sample(rate: float, *samples) -> List:
    """Keep samples with probability `rate`, marking the effective sample
    rate (samples.go:128-134 RandomlySample)."""
    if rate >= 1.0:
        return list(samples)
    kept = []
    for s in samples:
        if random.random() < rate:
            s.sample_rate = rate
            kept.append(s)
    return kept
