from veneur_tpu.trace.client import (  # noqa: F401
    ChannelBackend,
    Client,
    PacketBackend,
    StreamBackend,
)
from veneur_tpu.trace.tracer import Span, Tracer  # noqa: F401
