from veneur_tpu.trace.client import (  # noqa: F401
    ChannelBackend,
    Client,
    PacketBackend,
    StreamBackend,
)
from veneur_tpu.trace.tracer import Span, Tracer  # noqa: F401
from veneur_tpu.trace.opentracing import (  # noqa: F401
    GLOBAL_TRACER,
    HEADER_FORMATS,
    OpenTracingTracer,
    SpanContext,
)
