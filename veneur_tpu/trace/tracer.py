"""Span construction + a minimal tracer (reference trace/trace.go span
lifecycle and trace/opentracing.go header inject/extract).

The reference exposes a full OpenTracing adapter; the API here covers the
parts Veneur itself uses: StartSpan/start_span_from_context, tags,
ClientFinish, and HTTP header propagation (trace id / span id headers,
opentracing.go textmap carrier)."""

from __future__ import annotations

import random
import time
from typing import Dict, Optional

from veneur_tpu.proto import ssf_pb2

HEADER_TRACE_ID = "Trace-Id"
HEADER_SPAN_ID = "Span-Id"


def _new_id() -> int:
    return random.getrandbits(63) | 1


class Span:
    def __init__(self, name: str, service: str = "",
                 trace_id: Optional[int] = None,
                 parent_id: Optional[int] = None,
                 indicator: bool = False, tags: Optional[Dict] = None,
                 start_ns: Optional[int] = None):
        self.name = name
        self.service = service
        self.trace_id = trace_id or _new_id()
        self.id = _new_id()
        self.parent_id = parent_id or 0
        self.indicator = indicator
        self.error = False
        self.tags = dict(tags or {})
        # explicit start supports spans reconstructed after the fact (the
        # flush trace's ingest-drain phase happens on the pipeline thread
        # BEFORE the flush worker builds the span tree)
        self.start_ns = (int(start_ns) if start_ns is not None
                         else int(time.time() * 1e9))
        self.end_ns = 0
        self.samples = []
        self.log_lines = []   # LogFields/LogKV records (stored, unsent —
        #                       matching opentracing.go:312 "ignored")
        self.baggage: Dict[str, str] = {}

    def set_tag(self, k: str, v) -> "Span":
        self.tags[k] = v if isinstance(v, str) else repr(v)
        return self

    def set_operation_name(self, name: str) -> "Span":
        """OpenTracing SetOperationName -> the resource tag
        (opentracing.go:278 sets Trace.Resource)."""
        self.tags["resource"] = name
        return self

    def log_fields(self, **fields) -> None:
        self.log_lines.append(dict(fields))

    def log_kv(self, *alternating) -> None:
        self.log_fields(**{str(alternating[i]): alternating[i + 1]
                           for i in range(0, len(alternating) - 1, 2)})

    def log_event(self, event: str) -> None:
        """Deprecated OpenTracing API — interface-compat no-op, exactly
        like the reference (opentracing.go:341 LogEvent)."""

    def log_event_with_payload(self, event: str, payload) -> None:
        """Deprecated no-op (opentracing.go:346)."""

    def log(self, data) -> None:
        """Deprecated no-op (opentracing.go:351)."""

    def set_baggage_item(self, key: str, value: str) -> "Span":
        """Span-level baggage, carried into context()/child contexts
        (opentracing.go:324 SetBaggageItem)."""
        self.baggage[key] = value
        return self

    def baggage_item(self, key: str) -> str:
        kl = key.lower()
        for k, v in self.baggage.items():
            if k.lower() == kl:
                return v
        return ""

    def finish_with_options(self, finish_time_ns: Optional[int] = None,
                            log_records=None) -> ssf_pb2.SSFSpan:
        """FinishWithOptions (opentracing.go:236): explicit finish time;
        log records are retained with the span's log lines but — like
        the reference — never transmitted (BulkLogData deprecated)."""
        if log_records:
            self.log_lines.extend(log_records)
        return self.finish(finish_time_ns)

    def context(self):
        from veneur_tpu.trace.opentracing import SpanContext
        return SpanContext.from_span(self)

    def add(self, *samples):
        """Attach SSF metric samples to ride along with the span
        (trace.go Span.Add)."""
        self.samples.extend(samples)

    def child(self, name: str, **kw) -> "Span":
        return Span(name, service=self.service, trace_id=self.trace_id,
                    parent_id=self.id, **kw)

    def finish(self, finish_time_ns: Optional[int] = None) -> ssf_pb2.SSFSpan:
        self.end_ns = finish_time_ns or int(time.time() * 1e9)
        return self.to_ssf()

    def to_ssf(self) -> ssf_pb2.SSFSpan:
        span = ssf_pb2.SSFSpan(
            version=0, trace_id=self.trace_id, id=self.id,
            parent_id=self.parent_id, service=self.service, name=self.name,
            indicator=self.indicator, error=self.error,
            start_timestamp=self.start_ns,
            end_timestamp=self.end_ns or int(time.time() * 1e9))
        for k, v in self.tags.items():
            span.tags[k] = v
        for s in self.samples:
            span.metrics.append(s)
        return span

    def client_finish(self, client) -> None:
        """finish + record on the trace client (trace.go ClientFinish)."""
        ssf_span = self.finish()
        if client is not None:
            client.record(ssf_span)

    # -- header propagation (opentracing.go inject/extract) -----------------
    def inject(self, headers: Dict[str, str]) -> None:
        headers[HEADER_TRACE_ID] = str(self.trace_id)
        headers[HEADER_SPAN_ID] = str(self.id)


class Tracer:
    def __init__(self, service: str = "", client=None):
        self.service = service
        self.client = client

    def start_span(self, name: str, parent: Optional[Span] = None,
                   **kw) -> Span:
        if parent is not None:
            s = parent.child(name, **kw)
        else:
            s = Span(name, service=self.service, **kw)
        return s

    def extract(self, headers: Dict[str, str],
                name: str = "request") -> Span:
        """Continue a trace from incoming HTTP headers; malformed ids fall
        back to a fresh trace (headers are caller-controlled)."""
        def _id(key):
            try:
                return int(headers.get(key, 0) or 0) or None
            except (TypeError, ValueError):
                return None

        return Span(name, service=self.service,
                    trace_id=_id(HEADER_TRACE_ID),
                    parent_id=_id(HEADER_SPAN_ID))
