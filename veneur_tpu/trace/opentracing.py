"""OpenTracing-compatible surface (reference trace/opentracing.go).

The reference implements the opentracing-go interfaces; Python has no
equivalent dependency baked in, so this module provides the same
capabilities idiomatically: a `SpanContext` carrying baggage, a tracer
that injects/extracts trace identity across the reference's FOUR
supported header conventions (opentracing.go:38-66 HeaderFormats) with
the same precedence and number bases, and request helpers mirroring
InjectRequest/ExtractRequestChild (:486-523).

Header formats, tried in order on extract (case-insensitive):
  1. Envoy/Lightstep  ot-tracer-traceid / ot-tracer-spanid   (hex)
  2. OpenTracing      Trace-Id / Span-Id                     (decimal)
  3. Ruby             X-Trace-Id / X-Span-Id                 (decimal)
  4. Veneur           Traceid / Spanid                       (decimal)
Inject writes format 1 (the default, opentracing.go:69) plus its
static outgoing headers (ot-tracer-sampled: true).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from veneur_tpu.trace.tracer import Span, Tracer

RESOURCE_KEY = "resource"


@dataclass
class HeaderGroup:
    trace_id: str
    span_id: str
    hexadecimal: bool = False
    outgoing: Dict[str, str] = field(default_factory=dict)


HEADER_FORMATS = [
    HeaderGroup("ot-tracer-traceid", "ot-tracer-spanid", hexadecimal=True,
                outgoing={"ot-tracer-sampled": "true"}),
    HeaderGroup("Trace-Id", "Span-Id"),
    HeaderGroup("X-Trace-Id", "X-Span-Id"),
    HeaderGroup("Traceid", "Spanid"),
]
DEFAULT_HEADER_FORMAT = HEADER_FORMATS[0]


class SpanContext:
    """Trace identity + baggage (opentracing.go:128 spanContext). Keys
    are case-insensitive like the reference's parseBaggageInt64."""

    def __init__(self, baggage: Optional[Dict[str, str]] = None):
        self.baggage: Dict[str, str] = dict(baggage or {})

    def _get(self, key: str) -> str:
        kl = key.lower()
        for k, v in self.baggage.items():
            if k.lower() == kl:
                return v
        return ""

    def _get_int(self, key: str) -> int:
        try:
            return int(self._get(key) or 0)
        except ValueError:
            return 0

    @property
    def trace_id(self) -> int:
        return self._get_int("traceid")

    @property
    def span_id(self) -> int:
        return self._get_int("spanid")

    @property
    def parent_id(self) -> int:
        return self._get_int("parentid")

    @property
    def resource(self) -> str:
        return self._get(RESOURCE_KEY)

    def set_baggage_item(self, key: str, value: str) -> "SpanContext":
        self.baggage[key] = value
        return self

    def baggage_item(self, key: str) -> str:
        return self._get(key)

    @classmethod
    def from_span(cls, span: Span) -> "SpanContext":
        bag = {"traceid": str(span.trace_id),
               "spanid": str(span.id),
               "parentid": str(span.parent_id),
               RESOURCE_KEY: span.tags.get(RESOURCE_KEY, "")}
        # span-level baggage rides into the context (opentracing.go:265
        # contextAsParent + :324 SetBaggageItem); identity keys win
        for k, v in getattr(span, "baggage", {}).items():
            bag.setdefault(k, v)
        return cls(bag)


def span_context(span: Span) -> SpanContext:
    """span.Context() in the reference (opentracing.go:256)."""
    return SpanContext.from_span(span)


class OpenTracingTracer(Tracer):
    """Tracer + carrier inject/extract. Subclasses the core tracer so
    the server's existing start_span surface is unchanged."""

    def start_span_ot(self, operation_name: str = "", *, child_of=None,
                      follows_from=None, tags: Optional[Dict] = None,
                      start_time_ns: Optional[int] = None) -> Span:
        """The reference's opentracing StartSpan (opentracing.go:403):

        - no reference -> a new root trace;
        - child_of / follows_from (a Span or SpanContext) -> a child of
          the referenced context. FollowsFrom is treated IDENTICALLY to
          ChildOf, as the reference does ("Datadog treats children and
          follow-children the same way", opentracing.go:430);
        - a `name` tag overrides the operation name (:466);
        - an empty name falls back to the caller's function name (:473
          runtime.Caller), so bare spans remain attributable;
        - start_time_ns overrides the span clock (customSpanStart).
        """
        ref = child_of if child_of is not None else follows_from
        if isinstance(ref, Span):
            ref = SpanContext.from_span(ref)
        if ref is not None:
            span = Span(operation_name, service=self.service,
                        trace_id=ref.trace_id or None,
                        parent_id=ref.span_id or None)
            if ref.resource:
                span.set_tag(RESOURCE_KEY, ref.resource)
            # parent baggage propagates to the child's context
            for k, v in ref.baggage.items():
                if k.lower() not in ("traceid", "spanid", "parentid",
                                     RESOURCE_KEY):
                    span.set_baggage_item(k, v)
        else:
            span = Span(operation_name, service=self.service)
        for k, v in (tags or {}).items():
            span.set_tag(k, v)
            if k == "name":
                span.name = str(v)
        if not span.name:
            import sys as _sys
            frame = _sys._getframe(1)
            span.name = frame.f_code.co_name
        if start_time_ns is not None:
            span.start_ns = start_time_ns
        return span

    # -- carriers ------------------------------------------------------------
    def inject(self, ctx, carrier: Dict[str, str],
               header_format: HeaderGroup = DEFAULT_HEADER_FORMAT) -> None:
        """Write trace identity into a dict-like carrier
        (opentracing.go:525 Inject + :486 InjectRequest)."""
        if isinstance(ctx, Span):
            ctx = SpanContext.from_span(ctx)
        trace_id, span_id = ctx.trace_id, ctx.span_id
        if header_format.hexadecimal:
            carrier[header_format.trace_id] = format(trace_id, "x")
            carrier[header_format.span_id] = format(span_id, "x")
        else:
            carrier[header_format.trace_id] = str(trace_id)
            carrier[header_format.span_id] = str(span_id)
        for k, v in header_format.outgoing.items():
            carrier[k] = v

    def extract_context(self, carrier: Dict[str, str]
                        ) -> Optional[SpanContext]:
        """Read trace identity from a carrier, trying each header
        convention in precedence order (opentracing.go:581 Extract).
        Returns None when no convention matches (the reference returns
        an error). Named distinctly from the base Tracer.extract, which
        keeps its always-succeeds Span-producing contract."""
        found = self._extract_ids(carrier)
        if found is None:
            return None
        trace_id, span_id = found
        return SpanContext({"traceid": str(trace_id),
                            "spanid": str(span_id)})

    @staticmethod
    def _carrier_get(carrier: Dict[str, str], key: str) -> str:
        kl = key.lower()
        for k, v in carrier.items():
            if k.lower() == kl:
                return v
        return ""

    def _extract_ids(self, carrier) -> Optional[Tuple[int, int]]:
        for fmt in HEADER_FORMATS:
            raw_t = self._carrier_get(carrier, fmt.trace_id)
            raw_s = self._carrier_get(carrier, fmt.span_id)
            if not raw_t and not raw_s:
                continue
            base = 16 if fmt.hexadecimal else 10
            try:
                trace_id, span_id = int(raw_t, base), int(raw_s, base)
            except ValueError:
                continue   # try the next convention, like the reference
            # the reference parses with strconv.ParseInt(..., 64): ids
            # outside int64 range are rejected and the next convention
            # tried — SSFSpan fields are int64 and would overflow
            if not (0 <= trace_id < 2 ** 63 and 0 <= span_id < 2 ** 63):
                continue
            return trace_id, span_id
        return None

    # -- request helpers -----------------------------------------------------
    def inject_header(self, span_or_ctx, headers: Dict[str, str]) -> None:
        """InjectHeader (opentracing.go:492)."""
        self.inject(span_or_ctx, headers)

    def extract_request_child(self, resource: str, headers: Dict[str, str],
                              name: str) -> Optional[Span]:
        """Continue an incoming request's trace as a child span
        (opentracing.go:499 ExtractRequestChild); None when the request
        carries no recognizable trace headers."""
        ctx = self.extract_context(headers)
        if ctx is None:
            return None
        span = Span(name, service=self.service,
                    trace_id=ctx.trace_id or None,
                    parent_id=ctx.span_id or None)
        if resource:
            span.set_tag(RESOURCE_KEY, resource)
        return span


GLOBAL_TRACER = OpenTracingTracer(service="veneur")
