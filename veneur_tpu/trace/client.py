"""SSF trace/metrics client (reference trace/client.go + backend.go).

A Client owns a bounded record queue (the `records` channel front-end with
backpressure, client.go:85-119) drained by one worker thread into a
backend:

- PacketBackend: one SSF protobuf per UDP/unixgram datagram
  (backend.go packetBackend).
- StreamBackend: framed spans over a stream socket, reconnecting with
  linear backoff (backend.go:18-31 DefaultBackoff 20ms → max 1s, connect
  timeout 10s; poison spans are dropped).
- ChannelBackend: feeds a server's own span pipeline directly — the
  self-telemetry loop (trace.NewChannelClient, server.go:309-313).
"""

from __future__ import annotations

import logging
import queue
import socket
import threading
import time
from typing import Optional

from veneur_tpu.protocol.wire import write_ssf

log = logging.getLogger("veneur_tpu.trace")

DEFAULT_CAPACITY = 1024
DEFAULT_BACKOFF = 0.020
MAX_BACKOFF = 1.0
CONNECT_TIMEOUT = 10.0


class PacketBackend:
    def __init__(self, address):
        self.address = address
        if isinstance(address, str):  # unixgram path
            self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        else:
            self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.connect(address)

    def send(self, span) -> None:
        self.sock.send(span.SerializeToString())

    def close(self):
        self.sock.close()


class StreamBackend:
    def __init__(self, address, backoff: float = DEFAULT_BACKOFF,
                 max_backoff: float = MAX_BACKOFF):
        self.address = address
        self.backoff = backoff
        self.max_backoff = max_backoff
        self.sock: Optional[socket.socket] = None
        self._closing = threading.Event()

    def prepare_close(self):
        """Unblocks a worker stuck in the reconnect loop so Client.close
        can join it."""
        self._closing.set()

    def _connect(self):
        delay = self.backoff
        while self.sock is None and not self._closing.is_set():
            try:
                if isinstance(self.address, str):
                    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                else:
                    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.settimeout(CONNECT_TIMEOUT)
                s.connect(self.address)
                s.settimeout(None)
                self.sock = s
            except OSError:
                time.sleep(delay)
                delay = min(delay + self.backoff, self.max_backoff)

    def send(self, span) -> None:
        if self.sock is None:
            self._connect()
        if self.sock is None:  # closing while disconnected
            raise OSError("backend closing")
        try:
            f = self.sock.makefile("wb")
            write_ssf(f, span)
            f.flush()
        except OSError:
            # drop the poison span, reconnect for the next one
            # (backend.go stream semantics)
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
            raise

    def close(self):
        if self.sock is not None:
            self.sock.close()


class ChannelBackend:
    """Direct hand-off into a SpanPipeline (self-telemetry loop-back)."""

    def __init__(self, span_pipeline):
        self.span_pipeline = span_pipeline

    def send(self, span) -> None:
        self.span_pipeline.handle_span(span)

    def close(self):
        pass


class Client:
    def __init__(self, backend, capacity: int = DEFAULT_CAPACITY):
        self.backend = backend
        self.records: "queue.Queue" = queue.Queue(maxsize=capacity)
        self.sent = 0
        self.dropped = 0
        self.errors = 0
        self._stop = object()
        self._thread = threading.Thread(target=self._work, daemon=True,
                                        name="trace-client")
        self._thread.start()

    def record(self, span) -> bool:
        """Non-blocking enqueue; full buffer drops (client.go backpressure
        semantics for the non-blocking path)."""
        try:
            self.records.put_nowait(span)
            return True
        except queue.Full:
            self.dropped += 1
            return False

    def _work(self):
        while True:
            item = self.records.get()
            try:
                if item is self._stop:
                    return
                try:
                    self.backend.send(item)
                    self.sent += 1
                except Exception:
                    self.errors += 1
            finally:
                self.records.task_done()

    def flush(self, timeout: float = 5.0):
        """Wait until every enqueued record has been fully sent (not just
        dequeued — task_done fires after backend.send returns)."""
        deadline = time.time() + timeout
        while self.records.unfinished_tasks and time.time() < deadline:
            time.sleep(0.01)

    def close(self):
        prepare = getattr(self.backend, "prepare_close", None)
        if prepare is not None:
            prepare()
        self.records.put(self._stop)
        self._thread.join(timeout=2.0)
        self.backend.close()


def report_one(client: Client, sample) -> bool:
    """Ship one SSF metric sample inside a metrics-only span (reference
    trace/metrics/client.go:21 ReportOne)."""
    return report_batch(client, [sample])


def report_batch(client: Client, samples) -> bool:
    """trace/metrics/client.go:50 ReportBatch: a span carrying only
    metrics (no trace fields) — the carrier-packet pattern."""
    from veneur_tpu.proto import ssf_pb2
    span = ssf_pb2.SSFSpan()
    for s in samples:
        span.metrics.append(s)
    return client.record(span)
