"""Self-adjusting key tables (ISSUE 20).

Live, per-kind key-table growth and pressure management: the system
absorbs cardinality explosions (10M live names) without a restart and
without unaccounted loss.

Three pieces, three failure ladders:

- growth.py — the ONE sanctioned grow site. Per-kind capacity changes
  execute at the pipeline-thread swap boundary, reusing the staged-
  then-applied-at-reset discipline of reshard/quiesce.py (the vtlint
  `table-grow-quiesce` pass makes any other mutation site a finding).
  Growth only re-sizes *within* a shard: `route_digest % n_shards`
  shard assignment is capacity-independent, so the C++ preshard emit
  path stays byte-identical across a grow (fuzz-pinned).
- pressure.py — the ladder below hard capacity for Python key tables:
  SALSA-style merge cells for long-tail counters (arXiv:2102.12531,
  pinned additive error bound), tag-explosion demotion to aggregate-
  only rollup rows (per-key-family generalization of the per-tenant
  quarantine, arXiv:2004.10332), and exact counted drops as the last
  rung. Every non-admitted row is accounted.
- manager.py — occupancy census, grow/shrink planning, TTL eviction
  accounting, and the snapshot sidecar state ("keytables" chunk) that
  lets a checkpoint restore re-grow before folding rows.
"""

from veneur_tpu.tables.growth import adopt_capacities, grow_swap, grown_spec
from veneur_tpu.tables.manager import TableManager
from veneur_tpu.tables.pressure import TablePressure

__all__ = ["TableManager", "TablePressure", "adopt_capacities",
           "grow_swap", "grown_spec"]
