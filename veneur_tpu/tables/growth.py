"""The ONE sanctioned key-table grow site (ISSUE 20 tentpole a).

Growth reuses the reshard drain's staged-then-applied-at-reset
discipline (reshard/quiesce.py): new per-kind capacities are STAGED on
the C++ engine under its key mutex (`capacity_set` → pending_caps),
then APPLIED by the `vt_reset` that runs inside the very next swap's
quiesce — while the engine's tables are empty and (for the multi-ring
group) the ring workers are paused. Key tables are flush-scoped (every
swap builds a fresh table from spec on both the Python and C++ paths),
so a grow needs NO mid-interval rehash at all: the grow pause IS the
swap pause, bounded at one flush interval by construction.

Shard assignment (`route_digest % n_shards`, host.py slot rule) is
capacity-independent, so growth only changes a shard's slot budget —
the C++ preshard emit path's shard split stays byte-identical across a
grow (pinned by the fuzz test in tests/test_tables.py).

The vtlint `table-grow-quiesce` pass makes this module (plus the ctypes
binding layer) the only place allowed to call the capacity mutators;
any other grow site is a finding.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, Optional, Tuple

log = logging.getLogger("veneur.tables")

# table kind -> TableSpec field, in the native capacity_set argument
# order for the first four (status is Python-side on every backend)
KIND_FIELDS = (("counter", "counter_capacity"),
               ("gauge", "gauge_capacity"),
               ("set", "set_capacity"),
               ("histo", "histo_capacity"),
               ("status", "status_capacity"))


def spec_capacities(spec) -> Dict[str, int]:
    """Per-kind capacities of a TableSpec, by table kind."""
    return {k: int(getattr(spec, f)) for k, f in KIND_FIELDS}


def grown_spec(spec, targets: Dict[str, int]):
    """A new TableSpec with the given per-kind capacities applied.
    Only capacity fields change — sketch geometry (compression, HLL
    precision, ...) is identity-relevant and never grows live."""
    fields = dict(KIND_FIELDS)
    changes = {fields[k]: int(v) for k, v in targets.items()
               if k in fields and int(v) != getattr(spec, fields[k])}
    return dataclasses.replace(spec, **changes) if changes else spec


class GrowConflict(RuntimeError):
    """Grow refused because a conflicting live operation (reshard) owns
    the swap boundary; carries .status = 409 for admin surfaces."""

    status = 409


def grow_swap(server, new_spec) -> Tuple[object, object, object]:
    """Execute a per-kind capacity change at the swap boundary.

    MUST run on the pipeline thread (it IS the interval flush swap).
    Returns (state, table, old_aggregator) — the detached interval,
    which the caller enqueues as this interval's flush job exactly like
    a plain swap; the flush math runs against the OLD aggregator's spec.

    Sequence (mirrors reshard/coordinator.py `_begin_on_pipeline`):
    stage capacities on the engine → swap (the quiesce's reset applies
    them while tables are empty) → rebuild the backend around the SAME
    engine with the new spec → carry the lifetime counters over →
    install. Ingest never restarts; readers keep feeding the same C++
    handle throughout.
    """
    old = server.aggregator
    eng = getattr(old, "eng", None)
    if eng is not None:
        caps = spec_capacities(new_spec)
        eng.capacity_set(caps["counter"], caps["gauge"], caps["set"],
                         caps["histo"])
    state, table = old.swap()
    new_agg, native = server._make_aggregator(
        getattr(old, "n_shards", 1), engine=eng, spec=new_spec)
    # lifetime-counter continuity (same set the reshard drain carries)
    new_agg.processed = old.processed
    new_agg.dropped_capacity = old.dropped_capacity
    new_agg.h2d_bytes = getattr(old, "h2d_bytes", 0)
    new_agg.last_set_shift = getattr(old, "last_set_shift", 0)
    if getattr(old, "_pressure", None) is not None:
        new_agg.set_pressure(old._pressure)
    server.aggregator = new_agg
    server._native = native
    log.info("key tables grown: %s -> %s",
             spec_capacities(old.spec), spec_capacities(new_spec))
    return state, table, old


def adopt_capacities(server, caps: Dict[str, int]) -> bool:
    """Restore-time re-grow: adopt a checkpoint sidecar's per-kind
    capacities BEFORE folding rows. Startup only — the pipeline is not
    running yet, so the swap boundary is trivially quiescent and the
    discarded empty interval costs nothing. Returns True if the spec
    changed. fold_snapshot is capacity-independent (restore.py digest
    routing), so folding works either way; adopting first means the
    restored process starts with the table headroom it had when the
    checkpoint was taken instead of re-walking the grow ladder."""
    spec = server.aggregator.spec
    new_spec = grown_spec(spec, caps)
    if new_spec is spec:
        return False
    n_shards = getattr(server.aggregator, "n_shards", 1)
    bad = [k for k, v in spec_capacities(new_spec).items()
           if v <= 0 or v % n_shards]
    if bad:
        log.warning("checkpoint capacities %s not adoptable at "
                    "n_shards=%d; restoring at config capacities",
                    caps, n_shards)
        return False
    grow_swap(server, new_spec)
    return True
