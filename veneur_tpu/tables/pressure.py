"""Pressure ladder below hard key-table capacity (ISSUE 20 tentpole b).

Attached to every flush interval's fresh Python KeyTable by the
backend's swap() (Aggregator.set_pressure); the native C++ engine keeps
its exact counted drops instead — those are absorbed by the next grow,
which is the native path's pressure valve.

The ladder runs on the slot-allocation MISS path only (the hit path
stays one dict probe — host.py KeyTable.slot_for), in order:

1. demotion  — a key family (table kind, metric name) whose tag-variant
   allocation rate tripped the explosion detector sends every NEW
   variant to one aggregate-only rollup row tagged
   `veneur_rollup:true`; the exact count of collapsed variants is
   `demoted_rows_total`. This is PR 19's per-tenant quarantine
   generalized to per-key-family (arXiv:2004.10332's bucketed
   aggregation under cardinality pressure).
2. admission — room in the key's shard: normal allocation. The shard
   check runs BEFORE t.alloc so a ladder fall-through never
   double-counts `dropped`.
3. merging   — counters only: a full shard redirects the key to one of
   the SALSA merge cells pre-allocated at attach (arXiv:2102.12531's
   self-adjusting cell merge: neighbors share a cell, value mass is
   conserved). Counted once per distinct merged key per interval as
   `merged_cells_total`. Error bound: a merge cell's value is the EXACT
   sum of its members' increments, so any single member's value is
   over-reported by at most the cell total minus its own contribution
   (additive, pinned by tests/test_tables.py).
4. drop      — exact counted drop (`t.dropped`), already policed by the
   PR 4 drop-accounting lint.

Redirects install a by_key alias, so every later sample of a demoted or
merged key takes the one-probe hit path — the ladder itself is paid
once per distinct key per interval.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

# counter name that owns the SALSA merge cells; the reserved tag marks
# rollup rows so downstream consumers can tell exact rows from
# aggregate-only ones
MERGE_CELL_NAME = "veneur.table.overflow"
ROLLUP_TAG = "veneur_rollup:true"


class TablePressure:
    """Cross-interval pressure state shared by successive KeyTables.

    Counters (`merged`, `demoted`) are cumulative across intervals and
    keyed by table kind — the registry's labeled-by-kind families read
    them directly. Variant-rate estimators decay at each attach (one
    attach per flush interval), the same windowed-decay detector as
    reliability/tenancy.py's quarantine.
    """

    def __init__(self, salsa_enabled: bool = False, salsa_cells: int = 64,
                 demote_threshold: int = 4096, demote_decay: float = 0.5):
        self.salsa_enabled = bool(salsa_enabled)
        self.salsa_cells = int(salsa_cells)
        self.demote_threshold = int(demote_threshold)
        self.demote_decay = float(demote_decay)
        # cumulative, by table kind ("counter"/"gauge"/"set"/"histo"/
        # "status") — exact accounting for the registry families
        self.merged: Dict[str, int] = {}
        self.demoted: Dict[str, int] = {}
        # tag-explosion detector: (kind, name) -> decayed variant-rate
        # estimate; window counts NEW variant allocations this interval
        self._est: Dict[Tuple[str, str], float] = {}
        self._window: Dict[Tuple[str, str], int] = {}
        self._demoted_families: set = set()
        # per-attach state
        self._kind_of: Dict[int, str] = {}       # id(_KindTable) -> kind
        self._cells: list = []                   # counter merge cell slots
        self._merged_keys: set = set()           # interval dedup for merged

    # -- interval boundary ---------------------------------------------------
    def attach(self, table) -> None:
        """Install on a fresh KeyTable (swap boundary, pipeline thread).
        Rolls the variant-rate window into the decayed estimate and
        pre-allocates the SALSA merge cells in the new counter table."""
        table.pressure = self
        self._kind_of = {id(t): k for k, t in table.tables.items()}
        # decay + roll the detector windows; prune quiet families so the
        # estimator map stays bounded by the active-family set
        if self._est or self._window:
            est = {}
            for fam in set(self._est) | set(self._window):
                v = (self._est.get(fam, 0.0) * self.demote_decay
                     + self._window.get(fam, 0))
                if v >= 1.0 or fam in self._demoted_families:
                    est[fam] = v
                if v >= self.demote_threshold:
                    self._demoted_families.add(fam)
            self._est = est
            self._window = {}
        self._merged_keys = set()
        self._cells = []
        if self.salsa_enabled:
            t = table.tables["counter"]
            for i in range(self.salsa_cells):
                key = ("counter", MERGE_CELL_NAME, f"cell:{i}")
                slot = t.by_key.get(key)
                if slot is None:
                    slot = t.alloc(key, i, MERGE_CELL_NAME, (f"cell:{i}",),
                                   0, "counter", joined_tags=f"cell:{i}")
                if slot is None:
                    break  # table smaller than the cell block: stop early
                self._cells.append(slot)

    # -- miss-path ladder ----------------------------------------------------
    def admit(self, t, key, digest: int, name: str, tags: tuple, scope: int,
              kind: str, hostname: str, imported: bool,
              joined_tags) -> Optional[int]:
        tkind = self._kind_of.get(id(t), kind)
        fam = (tkind, name)
        # 1. demoted family: collapse the variant onto the rollup row
        if fam in self._demoted_families and joined_tags != ROLLUP_TAG:
            rollup_key = (kind, name, ROLLUP_TAG)
            slot = t.by_key.get(rollup_key)
            if slot is None:
                slot = t.alloc(rollup_key, digest, name, (ROLLUP_TAG,),
                               scope, kind, hostname=hostname,
                               joined_tags=ROLLUP_TAG)
            if slot is not None:
                t.by_key[key] = slot  # alias: next sample hits fast path
                self.demoted[tkind] = self.demoted.get(tkind, 0) + 1
                return slot
            # rollup row itself unallocatable: fall through the ladder
        # 2. room in the key's shard: normal allocation (+ detector)
        shard = digest % t.n_shards
        if t.next_free[shard] < t.per_shard:
            w = self._window.get(fam, 0) + 1
            self._window[fam] = w
            if w + self._est.get(fam, 0.0) >= self.demote_threshold:
                self._demoted_families.add(fam)
            return t.alloc(key, digest, name, tags, scope, kind,
                           hostname=hostname, imported=imported,
                           joined_tags=joined_tags)
        # 3. SALSA merge cell (counters only): conserve the value mass
        if self._cells and tkind == "counter":
            slot = self._cells[digest % len(self._cells)]
            t.by_key[key] = slot
            if key not in self._merged_keys:
                self._merged_keys.add(key)
                self.merged[tkind] = self.merged.get(tkind, 0) + 1
            return slot
        # 4. exact counted drop (drop-accounting lint polices this)
        t.dropped += 1
        return None

    # -- registry snapshots --------------------------------------------------
    def merged_snapshot(self):
        return [((k,), v) for k, v in sorted(self.merged.items())]

    def demoted_snapshot(self):
        return [((k,), v) for k, v in sorted(self.demoted.items())]
