"""Key-table growth planning, idle-eviction census, and accounting.

The TableManager owns the policy half of ISSUE 20: WHEN to grow (or
shrink) which kind's table, and the exact accounting that makes every
non-admitted row visible. The mechanism half — executing a capacity
change at the swap boundary — lives in growth.py, the one site the
vtlint `table-grow-quiesce` pass allows.

Key tables are flush-scoped (a fresh table per interval), so "idle
eviction" is not a table operation at all: a key that stops arriving
simply occupies nothing next interval. What the census adds is exact
OBSERVABILITY of that reclamation — `(kind, key) -> last_seen`, swept
against `table_idle_ttl_s`, each expiry counted once in
`evicted_total` — plus the demand signal that lets capacity shrink
back after an explosion subsides. The census is bounded at CENSUS_MAX
entries; past that it disarms (eviction accounting reads 0, growth
still works) rather than competing with the flush for host time.
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Dict, Optional, Tuple

from veneur_tpu.tables.growth import spec_capacities

log = logging.getLogger("veneur.tables")

KINDS = ("counter", "gauge", "set", "histo", "status")


class TableManager:
    # census hard bound: beyond this the census costs more than the
    # observability is worth; growth/pressure keep running without it
    CENSUS_MAX = 1 << 20

    def __init__(self, baseline_spec, n_shards: int = 1,
                 max_capacity: int = 1 << 24, idle_ttl_s: float = 300.0,
                 high_water: float = 0.85, shrink_window: int = 8):
        self.baseline = spec_capacities(baseline_spec)
        self.n_shards = max(1, int(n_shards))
        self.max_capacity = int(max_capacity)
        self.idle_ttl_s = float(idle_ttl_s)
        self.high_water = float(high_water)
        # exact accounting, by kind (registry families read these)
        self.grows: Dict[str, int] = {}
        self.evicted: Dict[str, int] = {}
        self.grow_events = 0            # grow swaps executed (any kind)
        self.last_grow_swap_ns = 0      # pause cost of the last grow
        # occupancy history for the conservative shrink rule
        self._occ = {k: deque(maxlen=max(2, int(shrink_window)))
                     for k in KINDS}
        # native `dropped` is lifetime-cumulative; per-interval deltas
        self._prev_native_dropped: Dict[str, int] = {}
        # idle census
        self._census: Dict[Tuple[str, object], float] = {}
        self._census_on = True
        self._last_sweep = 0.0
        self.pressure = None            # set by the server when enabled
        self._forced: Optional[Dict[str, int]] = None

    # -- occupancy -----------------------------------------------------------
    def occupancy(self, agg) -> Dict[str, Tuple[int, int, int]]:
        """Per kind (used, dropped_this_interval, capacity) of the LIVE
        interval. Pipeline-thread only (the native stats call must not
        interleave with feed, and Python table reads race staging
        otherwise)."""
        out: Dict[str, Tuple[int, int, int]] = {}
        eng = getattr(agg, "eng", None)
        if eng is not None and hasattr(eng, "table_stats"):
            for k, (used, dropped_cum, cap) in eng.table_stats().items():
                prev = self._prev_native_dropped.get(k, 0)
                self._prev_native_dropped[k] = dropped_cum
                out[k] = (int(used), max(0, dropped_cum - prev), int(cap))
            st = getattr(agg.table, "status", None)
            if st is not None:
                out["status"] = (sum(st.next_free), st.dropped, st.capacity)
        else:
            for k, t in agg.table.tables.items():
                out[k] = (sum(t.next_free), t.dropped, t.capacity)
        return out

    # -- grow / shrink planning ----------------------------------------------
    def plan(self, agg) -> Optional[Dict[str, int]]:
        """Per-kind capacity targets for a grow swap at THIS flush
        boundary, or None. Pipeline-thread only. Growth doubles until
        demand (admitted + dropped rows, i.e. what WANTED a slot) fits
        under the high-water mark; shrink halves only after a full
        window of intervals at < 1/4 occupancy and never below the
        config baseline. Both directions preserve n_shards
        divisibility — doubling/halving keeps it, and the max-capacity
        clamp rounds down to a multiple."""
        if self._forced is not None:
            forced, self._forced = self._forced, None
            for kind in forced:
                self._occ[kind].clear()
            return forced
        targets: Dict[str, int] = {}
        for kind, (used, dropped, cap) in self.occupancy(agg).items():
            hist = self._occ.get(kind)
            if hist is not None:
                hist.append(used)
            demand = used + dropped
            if demand >= self.high_water * cap:
                target = cap
                while (demand >= self.high_water * target
                       and target < self.max_capacity):
                    target *= 2
                clamp = self.max_capacity - (self.max_capacity
                                             % self.n_shards)
                target = min(target, max(cap, clamp))
                if target > cap:
                    targets[kind] = target
                continue
            base = self.baseline.get(kind, cap)
            if (hist is not None and len(hist) == hist.maxlen
                    and cap > base and max(hist) < cap // 4):
                half = cap // 2
                if half >= base and half % self.n_shards == 0:
                    targets[kind] = half
        if not targets:
            return None
        for kind in targets:
            self._occ[kind].clear()
        return targets

    def force(self, targets: Dict[str, int]) -> None:
        """Stage an operator-requested capacity change for the next
        flush boundary (Server.trigger_table_grow). Validated here so
        the pipeline thread never sees an unexecutable plan."""
        bad = {k: v for k, v in targets.items()
               if k not in KINDS or int(v) <= 0
               or int(v) % self.n_shards}
        if bad or not targets:
            raise ValueError(
                f"invalid grow targets {bad or targets}: kinds must be "
                f"in {KINDS} with positive capacities divisible by "
                f"n_shards={self.n_shards}")
        self._forced = {k: int(v) for k, v in targets.items()}

    def note_grow(self, targets: Dict[str, int], swap_ns: int) -> None:
        """Account an executed grow swap (growth.grow_swap ran)."""
        self.grow_events += 1
        self.last_grow_swap_ns = int(swap_ns)
        for kind in targets:
            self.grows[kind] = self.grows.get(kind, 0) + 1

    # -- idle census ---------------------------------------------------------
    @staticmethod
    def _iter_meta(table):
        """(table_kind, [(slot, SlotMeta)]) pairs of a DETACHED table,
        Python KeyTable or finalized NativeKeyTable alike."""
        tables = getattr(table, "tables", None)
        if tables is not None:
            return [(k, t.meta) for k, t in tables.items()]
        out = [(k, m) for k, m in table.meta.items()]
        out.append(("status", table.status.meta))
        return out

    def census_flush(self, table, now: float) -> None:
        """Flush-worker side: mark the detached interval's keys live and
        expire idle ones (exact `evicted_total`). Runs OFF the pipeline
        thread against an immutable finalized table."""
        if not self._census_on:
            return
        census = self._census
        for kind, meta in self._iter_meta(table):
            for _slot, m in meta:
                jt = m.joined_tags if m.joined_tags is not None \
                    else ",".join(m.tags)
                census[(kind, (m.kind, m.name, jt))] = now
        if len(census) > self.CENSUS_MAX:
            self._census_on = False
            self._census = {}
            log.warning("table census disarmed at %d live keys "
                        "(> %d); evicted_total accounting paused",
                        len(census), self.CENSUS_MAX)
            return
        # amortized sweep: at most ~4 walks per TTL period
        if now - self._last_sweep < max(self.idle_ttl_s / 4.0, 1.0):
            return
        self._last_sweep = now
        expired = [k for k, seen in census.items()
                   if now - seen > self.idle_ttl_s]
        for k in expired:
            del census[k]
            kind = k[0]
            self.evicted[kind] = self.evicted.get(kind, 0) + 1

    # -- registry snapshots --------------------------------------------------
    def grows_snapshot(self):
        return [((k,), v) for k, v in sorted(self.grows.items())]

    def evicted_snapshot(self):
        return [((k,), v) for k, v in sorted(self.evicted.items())]

    @staticmethod
    def capacity_snapshot(spec):
        return [((k,), v) for k, v in sorted(spec_capacities(spec).items())]

    # -- checkpoint sidecar ("keytables" chunk) ------------------------------
    def snapshot_state(self, spec) -> dict:
        """Sidecar payload: the LIVE per-kind capacities (so restore
        re-grows before folding) plus the cumulative accounting. The
        capacities live here, NOT in schema_hash — cross-capacity
        restore stays legal (codec.py covers field NAMES only)."""
        out = {"capacities": spec_capacities(spec),
               "grows": dict(self.grows),
               "evicted": dict(self.evicted),
               "grow_events": self.grow_events}
        if self.pressure is not None:
            out["merged"] = dict(self.pressure.merged)
            out["demoted"] = dict(self.pressure.demoted)
        return out

    def restore_state(self, d: dict) -> None:
        """Adopt a sidecar's cumulative accounting (capacities are
        adopted separately by growth.adopt_capacities, before fold)."""
        for key, target in (("grows", self.grows),
                            ("evicted", self.evicted)):
            for k, v in dict(d.get(key) or {}).items():
                if k in KINDS:
                    target[k] = int(v)
        self.grow_events = int(d.get("grow_events", self.grow_events))
        if self.pressure is not None:
            for key, target in (("merged", self.pressure.merged),
                                ("demoted", self.pressure.demoted)):
                for k, v in dict(d.get(key) or {}).items():
                    if k in KINDS:
                        target[k] = int(v)
