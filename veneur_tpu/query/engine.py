"""Batching query engine: many concurrent reads, one device launch.

Dashboard reads arrive as independent HTTP requests; the engine
coalesces everything that shows up within a `query_timeout_ms` window
(capped at `query_max_batch` queries) into ONE pipeline snapshot and
ONE device launch. The launch IS the flush program —
`flush_live_in_packed`, the same jitted executable `compute_flush`
tiles over — fed with the union of the batch's quantile vectors and
the per-kind slot gathers the batch resolved. Running the identical
program on the identical captured state is what makes query answers
value-exact vs what the next flush would export:

- histogram/timer quantiles go through the Pallas quantile kernel on
  TPU (ops/pallas_digest.py) and the XLA vmap fallback on CPU, exactly
  as the flush does;
- HLL cardinalities come from the 6-bit packed i32 rows entirely on
  device (ops/hll.estimate_packed_rows — no host unpack);
- counters and histogram count/sum/recip scalars leave the device as
  two-float (hi, lo) pairs and are folded in float64 by
  combine_flush_scalars, the flush's own residual fold;
- live-interval set estimates are scaled by 2^active_set_shift here,
  mirroring the latched-shift correction server._do_flush applies.

Sharded backends flatten their [replica, shard, rows] state views with
free reshapes (global slot = shard·per_shard + local IS the flat
index), so a gather touches only the owner shard's rows; a
collective-attached tier with >1 replicas runs its ICI register-max
merge first so reads see the mesh-global sketches.

A batch takes TWO pipeline-queue visits (see query/snapshot.py for
the donation rationale): SnapshotRequest pins the interval's naming
view, the engine resolves names to slots off-thread, then a
PipelineCall dispatches `_launch` FROM the pipeline thread — enqueued
in FIFO order before any later donating ingest step, so the live
state buffers are still valid when the gather reads them. Only the
async dispatch (~µs) runs on the pipeline thread; compilation of the
query's bucket shape is a one-time cost per shape, and host
materialization, unpacking, and response assembly all happen on the
engine's own thread. An intervening swap() between the two visits is
detected by table identity and the batch retries against the fresh
interval, so a response never mixes two table versions.

The dispatch site is on the vtlint jax-hot-path/timer-sync scan
lists: launch cost is recorded under `dispatch_ns` (enqueue-only by
naming convention) and device completion is sampled through the ONE
sanctioned sync point, `observability/jaxruntime.sync_and_time`,
every `_SYNC_EVERY` launches — on the engine thread, never the
pipeline's.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from fnmatch import fnmatchcase
from typing import Dict, List, Optional, Tuple

import numpy as np

from veneur_tpu.observability import jaxruntime
from veneur_tpu.query.nameindex import NameIndex
from veneur_tpu.query.snapshot import (COUNT_TABLES, PipelineCall,
                                       SnapshotRequest)

log = logging.getLogger("veneur_tpu.query")

_SYNC_EVERY = 64            # sampled device-sync cadence (1 in N launches)
_SNAPSHOT_TIMEOUT_S = 30.0  # pipeline round-trip budget (CPU CI: a
                            # flush storm can hold the queue for seconds)
_SUBMIT_TIMEOUT_S = 30.0    # end-to-end budget an HTTP thread waits
_MAX_MATCHES = 1024         # per-query resolution cap (truncated flag set)
_MAX_QUANTILES = 64         # per-query quantile-vector cap

KINDS = ("counter", "gauge", "status", "set", "histogram", "timer")
_KIND_TABLE = {"counter": "counter", "gauge": "gauge", "status": "status",
               "set": "set", "histogram": "histo", "timer": "histo"}
_DEFAULT_QS = (0.5, 0.9, 0.99)


class QueryError(ValueError):
    """Client error in a /query request body (HTTP 400)."""


class _IntervalRolled(Exception):
    """swap() ran between the naming snapshot and the launch visit;
    the batch retries against the fresh interval."""


def _parse_one(q) -> dict:
    if not isinstance(q, dict):
        raise QueryError("each query must be a JSON object")
    modes = [k for k in ("name", "prefix", "match") if k in q]
    if len(modes) != 1:
        raise QueryError(
            "each query needs exactly one of name/prefix/match")
    mode = modes[0]
    arg = q[mode]
    if not isinstance(arg, str):
        raise QueryError(f"{mode} must be a string")
    kinds = q.get("kinds")
    if kinds is None and "kind" in q:
        kinds = [q["kind"]]
    if kinds is not None:
        if (not isinstance(kinds, (list, tuple)) or not kinds
                or any(k not in KINDS for k in kinds)):
            raise QueryError(f"kind(s) must be drawn from {KINDS}")
        kinds = tuple(kinds)
    qs = q.get("quantiles")
    if qs is not None:
        if not isinstance(qs, (list, tuple)) or not qs \
                or len(qs) > _MAX_QUANTILES:
            raise QueryError(
                f"quantiles must be a list of 1..{_MAX_QUANTILES} floats")
        try:
            qs = tuple(sorted({float(v) for v in qs}))
        except (TypeError, ValueError):
            raise QueryError("quantiles must be numbers")
        if any(not (0.0 <= v <= 1.0) for v in qs):
            raise QueryError("quantiles must lie in [0, 1]")
    tags = q.get("tags")
    if tags is not None:
        if not isinstance(tags, (list, tuple)) \
                or any(not isinstance(t, str) for t in tags):
            raise QueryError("tags must be a list of strings")
        tags = tuple(tags)

    def _seconds(field):
        v = q.get(field)
        if v is None:
            return None
        try:
            v = float(v)
        except (TypeError, ValueError):
            raise QueryError(f"{field} must be a number of seconds")
        if not (0.0 < v < float("inf")):
            raise QueryError(f"{field} must be positive seconds")
        return v

    rng = _seconds("range")
    window = _seconds("window")
    step = _seconds("step")
    if rng is None and (window is not None or step is not None):
        raise QueryError("window/step only apply with range")
    return {"mode": mode, "arg": arg, "kinds": kinds,
            "quantiles": qs, "tags": tags,
            "range": rng, "window": window, "step": step}


def parse_request(body, max_queries: int) -> List[dict]:
    """POST /query body -> validated query list. Accepts
    {"queries": [...]} or a single bare query object."""
    if isinstance(body, dict) and "queries" in body:
        raw = body["queries"]
        if not isinstance(raw, list):
            raise QueryError("queries must be a list")
    elif isinstance(body, dict) and body:
        raw = [body]
    else:
        raise QueryError("empty query request")
    if not raw:
        raise QueryError("empty query request")
    if len(raw) > max_queries:
        raise QueryError(f"too many queries in one request "
                         f"(max {max_queries})")
    return [_parse_one(q) for q in raw]


class _Item:
    """One HTTP request's parsed queries + its completion slot."""

    __slots__ = ("queries", "done", "result", "error")

    def __init__(self, queries: List[dict]) -> None:
        self.queries = queries
        self.done = threading.Event()
        self.result: Optional[dict] = None
        self.error: Optional[Exception] = None


class QueryEngine:
    """Leader thread that batches, snapshots, launches and assembles."""

    def __init__(self, server, *, max_batch: int = 64,
                 timeout_ms: float = 2.0, requests=None, batched=None,
                 duration=None, stale_reads=None, history=None) -> None:
        self._server = server
        self.spec = server.aggregator.spec           # TOTAL capacities
        self._history = history                      # HistoryWriter | None
        self.max_batch = max(1, int(max_batch))
        self.timeout_s = max(0.0, float(timeout_ms)) / 1000.0
        self._c_requests = requests
        self._c_batched = batched
        self._t_duration = duration
        self._c_stale_reads = stale_reads
        self._queue: "queue_mod.Queue[Optional[_Item]]" = queue_mod.Queue()
        self._stop = threading.Event()
        self._sync = jaxruntime.SampledSync(_SYNC_EVERY)
        self.dispatch_ns = 0
        self.launches_total = 0
        # one name index per (table identity, counts): a dashboard
        # polling the same interval pays the sort once
        self._index: Optional[NameIndex] = None
        self._index_key: Optional[tuple] = None
        self._index_table = None
        self._thread = threading.Thread(
            target=self._serve_loop, name="query-batcher", daemon=True)
        self._thread.start()

    # -- public API ----------------------------------------------------------
    def submit(self, body, timeout: float = _SUBMIT_TIMEOUT_S) -> dict:
        """Parse, join the current batch, wait for the leader. Raises
        QueryError (400) on a bad body, TimeoutError/RuntimeError (503)
        when the pipeline or device cannot serve."""
        queries = parse_request(body, self.max_batch)
        if self._history is None \
                and any(q["range"] is not None for q in queries):
            raise QueryError("range queries need the history tier "
                             "(history_enabled: false)")
        if self._c_requests is not None:
            self._c_requests.inc(len(queries))
        if self._stop.is_set():
            raise RuntimeError("query engine stopped")
        item = _Item(queries)
        self._queue.put(item)
        if not item.done.wait(timeout):
            raise TimeoutError("query timed out")
        if item.error is not None:
            raise item.error
        assert item.result is not None
        return item.result

    def close(self) -> None:
        self._stop.set()
        self._queue.put(None)
        self._thread.join(timeout=5.0)
        # wake anything still parked (shutdown race)
        while True:
            try:
                it = self._queue.get_nowait()
            except queue_mod.Empty:
                break
            if it is not None:
                it.error = RuntimeError("query engine stopped")
                it.done.set()

    # -- batching loop -------------------------------------------------------
    def _serve_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                if self._stop.is_set():
                    return
                continue
            batch = [item]
            total = len(item.queries)
            deadline = time.monotonic() + self.timeout_s
            while total < self.max_batch:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=rem)
                except queue_mod.Empty:
                    break
                if nxt is None:
                    break
                batch.append(nxt)
                total += len(nxt.queries)
            try:
                self._execute(batch, total)
            except Exception as e:  # noqa: BLE001 — waiters must wake
                log.exception("query batch failed")
                for it in batch:
                    if not it.done.is_set():
                        it.error = e
                        it.done.set()
            if self._stop.is_set():
                return

    # -- snapshot + index ----------------------------------------------------
    def _snapshot(self):
        req = SnapshotRequest()
        try:
            self._server.packet_queue.put(req, timeout=1.0)
        except queue_mod.Full:
            raise RuntimeError("pipeline backlogged; snapshot not scheduled")
        if not req.wait(_SNAPSHOT_TIMEOUT_S):
            raise RuntimeError("snapshot timed out")
        if not req.ok:
            raise RuntimeError(req.detail or "snapshot failed")
        return req.snapshot

    def _index_for(self, snap) -> NameIndex:
        key = (id(snap.table),
               tuple(snap.counts[t] for t in COUNT_TABLES))
        if self._index is not None and self._index_key == key:
            return self._index
        idx = NameIndex(snap.metas, snap.counts)
        # hold the table reference so the id() cache key stays unique
        self._index, self._index_key, self._index_table = idx, key, snap.table
        return idx

    # -- resolution ----------------------------------------------------------
    def _resolve(self, index: NameIndex, q: dict) -> List[tuple]:
        if q["kinds"] is not None:
            tables = list(dict.fromkeys(
                _KIND_TABLE[k] for k in q["kinds"]))
        else:
            tables = list(COUNT_TABLES)
        out = []
        for tname in tables:
            if q["mode"] == "name":
                ent = index.exact(tname, q["arg"])
            elif q["mode"] == "prefix":
                ent = index.prefix(tname, q["arg"])
            else:
                ent = index.match(tname, q["arg"])
            for pos, slot, meta in ent:
                if q["kinds"] is not None and tname == "histo" \
                        and meta.kind not in q["kinds"]:
                    continue
                if q["tags"] is not None \
                        and tuple(meta.tags) != q["tags"]:
                    continue
                out.append((tname, pos, slot, meta))
        return out

    # -- device launch -------------------------------------------------------
    def _launch(self, state, packed_inputs, n_q: int, buckets: tuple):
        """The query tier's ONE device dispatch (vtlint jax-hot-path +
        timer-sync covered): enqueue cost lands in dispatch_ns; the
        sampled completion sync runs later on the ENGINE thread."""
        from veneur_tpu.aggregation.step import flush_live_in_packed
        t0 = time.perf_counter_ns()
        out = flush_live_in_packed(state, packed_inputs, spec=self.spec,
                                   n_q=n_q, buckets=buckets)
        self.dispatch_ns += time.perf_counter_ns() - t0
        self.launches_total += 1
        return out

    def _launch_on_pipeline(self, aggregator, table, packed_inputs,
                            n_q: int, buckets: tuple, rargs=None):
        """Visit #2 body, pipeline-thread-only: re-drain staging,
        verify the interval the slots were resolved against is still
        live (swap() installs a fresh table object), and dispatch the
        gather while the state buffers are guaranteed undonated.
        Returns ((instant packed | None, range packed | None), live
        set_shift). With range work the ring joins the SAME dispatch
        (merge.query_combined — one launch for the mixed batch), under
        the writer's dispatch lock with a seq re-check so a flush that
        landed since planning forces a replan instead of silently
        reading re-purposed columns."""
        if packed_inputs is not None and aggregator.table is not table:
            raise _IntervalRolled()
        state, _table, set_shift = aggregator.query_snapshot()
        flat = (aggregator.query_flat_state(state)
                if packed_inputs is not None else None)
        if rargs is None:
            return (self._launch(flat, packed_inputs, n_q, buckets),
                    None), int(set_shift)
        hflat, hn_q, hsteps, hbuckets, hseq = rargs
        ring = self._history.acquire_read()
        try:
            if self._history.seq != hseq:
                raise _IntervalRolled()
            out = self._launch_combined(flat, packed_inputs, ring, hflat,
                                        n_q, buckets, hn_q, hsteps,
                                        hbuckets)
        finally:
            self._history.release_read()
        return out, int(set_shift)

    def _launch_combined(self, flat, packed_inputs, ring, hflat,
                         n_q, buckets, hn_q, hsteps, hbuckets):
        """Range / mixed dispatch — still ONE device launch (vtlint
        jax-hot-path + timer-sync covered, same discipline as
        _launch)."""
        from veneur_tpu.history import merge as hmerge
        hspec = self._history.spec
        t0 = time.perf_counter_ns()
        if packed_inputs is None:
            out = (None, hmerge.range_in_packed(
                ring, hflat, hspec=hspec, n_q=hn_q, n_steps=hsteps,
                buckets=hbuckets))
        else:
            out = hmerge.query_combined(
                flat, packed_inputs, ring, hflat, spec=self.spec,
                n_q=n_q, buckets=buckets, hspec=hspec, hn_q=hn_q,
                hsteps=hsteps, hbuckets=hbuckets)
        self.dispatch_ns += time.perf_counter_ns() - t0
        self.launches_total += 1
        return out

    # -- batch execution -----------------------------------------------------
    def _execute(self, batch: List[_Item], total: int) -> None:
        t0 = time.perf_counter_ns()
        plans = res = rinfo = rres = None
        qcol: dict = {}
        rqcol: dict = {}
        set_shift = 0
        for _attempt in range(2):
            try:
                (plans, res, qcol, set_shift,
                 rinfo, rres, rqcol) = self._plan_and_evaluate(batch)
                break
            except _IntervalRolled:
                # swap() landed between the two pipeline visits: the
                # resolved slots belong to the detached interval.
                # Retry once against the fresh table, then escalate
                continue
        else:
            # a flush storm keeps landing swaps inside the two-visit
            # window (manual trigger_flush loops; a timer interval
            # can't): fall back to ONE atomic pipeline visit that
            # snapshots, resolves and dispatches with no gap to roll
            # into. Costs index/resolution time on the pipeline thread,
            # so it is the escalation path, never the default.
            (plans, res, qcol, set_shift,
             rinfo, rres, rqcol) = self._evaluate_atomic(batch)
        dur = time.perf_counter_ns() - t0
        # stale-bounded availability during a live reshard: the serving
        # table answers before all moved rows folded, so rows in flight
        # may be missing for at most one flush interval. The answer is
        # still served (availability wins); it is MARKED so consumers
        # and the chaos drill can pin the guarantee. Range answers
        # inherit the mark only for their NEWEST window — history
        # columns older than the move are immutable.
        stale = bool(getattr(self._server, "reshard_active", False))
        if stale and self._c_stale_reads is not None:
            self._c_stale_reads.inc(len(batch))
        for item, per_q in plans:
            results = []
            for qi, (rows, truncated, q) in enumerate(per_q):
                if q["range"] is not None:
                    entry = self._render_range_entry(item, qi, q, rinfo,
                                                     rres, rqcol)
                else:
                    matches = [self._render(tname, r, meta, q, res, qcol)
                               for tname, r, meta in rows]
                    entry = {"matches": matches}
                    if truncated:
                        entry["truncated"] = True
                results.append(entry)
            item.result = {"results": results, "batched": total,
                           "set_shift": set_shift}
            if stale:
                item.result["stale_bounded"] = True
            if self._t_duration is not None:
                self._t_duration.observe(dur)
            item.done.set()

    def _plan(self, index: NameIndex, batch: List[_Item]):
        """Resolve every query in the batch against one name index:
        per-item render plans, the deduped per-table slot gathers, and
        the union quantile vector."""
        need: Dict[str, List[int]] = {t: [] for t in COUNT_TABLES}
        rowof: Dict[Tuple[str, int], int] = {}
        plans = []   # [(item, [(rows, truncated, q), ...])]
        union_qs = set()
        for item in batch:
            per_q = []
            for q in item.queries:
                if q["range"] is not None:
                    # range queries resolve against the HISTORY writer's
                    # key index (_plan_ranges), not the live interval
                    per_q.append(([], False, q))
                    continue
                ms = self._resolve(index, q)
                truncated = len(ms) > _MAX_MATCHES
                if truncated:
                    ms = ms[:_MAX_MATCHES]
                rows = []
                histo_hit = False
                for tname, pos, slot, meta in ms:
                    key = (tname, pos)
                    r = rowof.get(key)
                    if r is None:
                        r = len(need[tname])
                        rowof[key] = r
                        need[tname].append(slot)
                    rows.append((tname, r, meta))
                    histo_hit = histo_hit or tname == "histo"
                if histo_hit:
                    union_qs.update(q["quantiles"] or _DEFAULT_QS)
                per_q.append((rows, truncated, q))
            plans.append((item, per_q))
        return plans, need, union_qs

    def _build_inputs(self, need, union_qs):
        """Slot gathers + union quantiles -> the flush program's packed
        input buffer and static shape arguments (layout knowledge lives
        with the flush program in aggregation/step.py)."""
        from veneur_tpu.aggregation.step import pack_query_inputs
        return pack_query_inputs(
            self.spec, [need[t] for t in COUNT_TABLES], union_qs)

    def _materialize(self, packed, n_q, buckets, set_shift):
        """ENGINE-thread finish: sampled device sync, host transfer,
        unpack, residual fold, live set-shift correction."""
        from veneur_tpu.aggregation.step import (combine_flush_scalars,
                                                 flush_live_shapes,
                                                 unpack_flush)
        self._sync.tick(packed)
        out = unpack_flush(
            np.asarray(packed),
            flush_live_shapes(self.spec, *buckets, n_q))
        if self._c_batched is not None:
            self._c_batched.inc()
        res = combine_flush_scalars(out)
        # live-interval set estimates: the degrade ladder's sampling
        # shift has not been latched yet, so apply 2^active_set_shift
        # here — the same correction server._do_flush applies post-swap
        if set_shift:
            res = dict(res)
            res["set_estimate"] = (res["set_estimate"]
                                   * float(1 << set_shift))
        return res

    def _plan_and_evaluate(self, batch: List[_Item]):
        """Two-visit default: snapshot + off-thread resolution (both the
        live-interval index and the history writer's key index), then a
        pipeline-dispatched launch (if anything matched). A mixed
        instant+range batch still costs ONE launch (query_combined)."""
        snap = self._snapshot()
        index = self._index_for(snap)
        plans, need, union_qs = self._plan(index, batch)
        rinfo = self._plan_ranges(batch)
        has_instant = any(need[t] for t in COUNT_TABLES)
        has_range = rinfo is not None and not rinfo["empty"]
        if not has_instant and not has_range:
            return plans, None, {}, snap.set_shift, rinfo, None, {}
        inputs = n_q = buckets = None
        qcol: dict = {}
        rargs = None
        rqcol: dict = {}
        hn_q = hsteps = hbuckets = None
        if has_instant:
            inputs, n_q, buckets, qcol = self._build_inputs(
                need, union_qs)
        if has_range:
            (hflat, hn_q, hsteps, hbuckets,
             rqcol) = self._build_range_inputs(rinfo)
            rargs = (hflat, hn_q, hsteps, hbuckets, rinfo["seq"])
        call = PipelineCall(lambda agg: self._launch_on_pipeline(
            agg, snap.table, inputs, n_q, buckets, rargs))
        self._pipeline_put(call)
        if not call.wait(_SNAPSHOT_TIMEOUT_S):
            raise RuntimeError("query launch timed out")
        if not call.ok:
            if isinstance(call.exc, _IntervalRolled):
                raise call.exc
            raise RuntimeError(call.detail or "query launch failed")
        (packed, rpacked), set_shift = call.result
        res = (self._materialize(packed, n_q, buckets, set_shift)
               if packed is not None else None)
        rres = (self._materialize_range(rpacked, hn_q, hsteps, hbuckets,
                                        count_batch=packed is None)
                if rpacked is not None else None)
        return plans, res, qcol, set_shift, rinfo, rres, rqcol

    def _evaluate_atomic(self, batch: List[_Item]):
        """Escalation path: snapshot, resolution, and launch dispatch
        in ONE pipeline visit — immune to interval rolls because swap()
        runs on the same thread and cannot interleave. Range planning
        happens UNDER the writer's dispatch lock here, so the ring seq
        cannot advance between plan and dispatch either."""
        from veneur_tpu.query.snapshot import _META_KIND, QuerySnapshot
        want_range = (self._history is not None
                      and any(q["range"] is not None
                              for it in batch for q in it.queries))

        def fn(agg):
            state, table, set_shift = agg.query_snapshot()
            metas = {t: table.get_meta(_META_KIND[t])
                     for t in COUNT_TABLES}
            counts = {t: len(metas[t]) for t in COUNT_TABLES}
            snap = QuerySnapshot(table=table, metas=metas, counts=counts,
                                 set_shift=int(set_shift))
            index = self._index_for(snap)
            plans, need, union_qs = self._plan(index, batch)
            has_instant = any(need[t] for t in COUNT_TABLES)
            ring = None
            if want_range:
                ring = self._history.acquire_read()
            try:
                rinfo = self._plan_ranges(batch)
                has_range = rinfo is not None and not rinfo["empty"]
                if not has_instant and not has_range:
                    return (plans, (None, None), None,
                            snap.set_shift, rinfo, {})
                inputs = n_q = buckets = None
                qcol: dict = {}
                rqcol: dict = {}
                hn_q = hsteps = hbuckets = None
                if has_instant:
                    inputs, n_q, buckets, qcol = self._build_inputs(
                        need, union_qs)
                flat = (agg.query_flat_state(state)
                        if has_instant else None)
                if has_range:
                    (hflat, hn_q, hsteps, hbuckets,
                     rqcol) = self._build_range_inputs(rinfo)
                    out = self._launch_combined(
                        flat, inputs, ring, hflat, n_q, buckets,
                        hn_q, hsteps, hbuckets)
                else:
                    out = (self._launch(flat, inputs, n_q, buckets),
                           None)
                return (plans, out, (n_q, buckets, qcol,
                                     hn_q, hsteps, hbuckets),
                        snap.set_shift, rinfo, rqcol)
            finally:
                if ring is not None:
                    self._history.release_read()

        call = PipelineCall(fn)
        self._pipeline_put(call)
        if not call.wait(_SNAPSHOT_TIMEOUT_S):
            raise RuntimeError("query launch timed out")
        if not call.ok:
            raise RuntimeError(call.detail or "query launch failed")
        plans, out, shape, set_shift, rinfo, rqcol = call.result
        packed, rpacked = out
        if packed is None and rpacked is None:
            return plans, None, {}, set_shift, rinfo, None, rqcol
        n_q, buckets, qcol, hn_q, hsteps, hbuckets = shape
        res = (self._materialize(packed, n_q, buckets, set_shift)
               if packed is not None else None)
        rres = (self._materialize_range(rpacked, hn_q, hsteps, hbuckets,
                                        count_batch=packed is None)
                if rpacked is not None else None)
        return plans, res, qcol, set_shift, rinfo, rres, rqcol

    def _pipeline_put(self, item) -> None:
        try:
            self._server.packet_queue.put(item, timeout=1.0)
        except queue_mod.Full:
            raise RuntimeError("pipeline backlogged; query not scheduled")

    # -- range planning (history tier) ---------------------------------------
    def _resolve_range(self, keys, q: dict) -> List[tuple]:
        """Match one range query against the writer's key index snapshot
        ([(kind_idx, (kind, name, joined_tags), row)]). Same name/
        prefix/match + kinds + tags semantics as the instant resolver,
        over the RING's population (which outlives interval tables)."""
        mode, arg = q["mode"], q["arg"]
        tags_j = ",".join(q["tags"]) if q["tags"] is not None else None
        kinds = q["kinds"]
        out = []
        for k, key, row in keys:
            kind, name, jt = key
            if kinds is not None and kind not in kinds:
                continue
            if tags_j is not None and jt != tags_j:
                continue
            if mode == "name":
                ok = name == arg
            elif mode == "prefix":
                ok = name.startswith(arg)
            else:
                ok = fnmatchcase(name, arg)
            if ok:
                out.append((k, row, kind, name, jt))
        out.sort(key=lambda e: (e[0], e[3], e[4], e[1]))
        return out

    def _plan_ranges(self, batch: List[_Item]):
        """Resolve + plan every range query in the batch: one shared
        ring-row gather per kind, one concatenated step-selection mask
        (each query's steps occupy a contiguous slice), capped at
        merge.MAX_STEPS total. Returns None when the batch has no range
        queries or the tier is off."""
        from veneur_tpu.history import merge as hmerge
        if self._history is None:
            return None
        rqs = [(item, qi, q) for item in batch
               for qi, q in enumerate(item.queries)
               if q["range"] is not None]
        if not rqs:
            return None
        hist = self._history
        keys = hist.iter_keys()
        need: List[List[int]] = [[] for _ in range(5)]
        rowof: Dict[Tuple[int, int], int] = {}
        union_qs: set = set()
        specs: dict = {}
        sel_rows: list = []
        all_steps: list = []
        per_q: dict = {}
        rank = np.zeros(hist.spec.total_cols, np.float32)
        planned_seq = hist.seq
        for item, qi, q in rqs:
            matches = self._resolve_range(keys, q)
            truncated = len(matches) > _MAX_MATCHES
            if truncated:
                matches = matches[:_MAX_MATCHES]
            rows = []
            histo_hit = False
            for k, row, kind, name, jt in matches:
                key = (k, row)
                r = rowof.get(key)
                if r is None:
                    r = len(need[k])
                    rowof[key] = r
                    need[k].append(row)
                rows.append((k, r, kind, name, jt))
                histo_hit = histo_hit or k == 4
            if histo_hit:
                union_qs.update(q["quantiles"] or _DEFAULT_QS)
            skey = (q["range"], q["window"], q["step"])
            ent = specs.get(skey)
            if ent is None:
                room = hmerge.MAX_STEPS - len(all_steps)
                if room <= 0:
                    # step budget spent by earlier specs in the batch:
                    # this query renders empty + truncated rather than
                    # growing the launch past its compiled step cap
                    ent = (0, [], True)
                else:
                    plan = hist.plan_range(skey[0], skey[1], skey[2],
                                           room)
                    ent = (len(all_steps), plan.steps, False)
                    all_steps.extend(plan.steps)
                    sel_rows.append(plan.sel)
                    rank = plan.rank
                specs[skey] = ent
            per_q[(id(item), qi)] = (rows, truncated or ent[2],
                                     ent[0], ent[1])
        sel = (np.concatenate(sel_rows, axis=0) if sel_rows
               else np.zeros((1, hist.spec.total_cols), np.float32))
        return {"per_q": per_q, "need": need, "union_qs": union_qs,
                "sel": sel, "rank": rank, "seq": planned_seq,
                "empty": not any(need)}

    def _build_range_inputs(self, rinfo):
        from veneur_tpu.history import merge as hmerge
        return hmerge.pack_range_inputs(
            self._history.spec, rinfo["need"], rinfo["sel"],
            rinfo["rank"], rinfo["union_qs"])

    def _materialize_range(self, rpacked, hn_q, hsteps, hbuckets,
                           count_batch: bool = False):
        """ENGINE-thread finish for the range half: sampled sync, host
        transfer, unpack, f64 residual folds. Set estimates come back
        UNSCALED: history windows were written from their own
        intervals' raw registers, and a degrade-ladder sampling shift
        is not retroactive (documented in README §History)."""
        from veneur_tpu.aggregation.step import unpack_flush
        from veneur_tpu.history import merge as hmerge
        self._sync.tick(rpacked)
        out = unpack_flush(
            np.asarray(rpacked),
            hmerge.range_shapes(self._history.spec, hbuckets, hsteps,
                                hn_q))
        if count_batch and self._c_batched is not None:
            self._c_batched.inc()
        f64 = np.float64
        return {
            "counter": (out["r_counter_hi"].astype(f64)
                        + out["r_counter_lo"].astype(f64)),
            "gauge": out["r_gauge"],
            "status": out["r_status"],
            "set_estimate": out["r_set_estimate"],
            "histo_quantiles": out["r_histo_quantiles"],
            "histo_min": out["r_histo_min"],
            "histo_max": out["r_histo_max"],
            "histo_count": (out["r_histo_count_hi"].astype(f64)
                            + out["r_histo_count_lo"].astype(f64)),
            "histo_sum": (out["r_histo_sum_hi"].astype(f64)
                          + out["r_histo_sum_lo"].astype(f64)),
        }

    # -- response assembly ---------------------------------------------------
    @staticmethod
    def _f(v):
        v = float(v)
        return v if np.isfinite(v) else None

    def _render(self, tname: str, r: int, meta, q: dict, res, qcol) -> dict:
        out = {"name": meta.name, "kind": meta.kind,
               "tags": list(meta.tags)}
        if tname == "counter":
            out["value"] = self._f(res["counter"][r])
        elif tname == "gauge":
            out["value"] = self._f(res["gauge"][r])
        elif tname == "status":
            out["value"] = self._f(res["status"][r])
            out["message"] = getattr(meta, "message", "") or ""
        elif tname == "set":
            out["estimate"] = self._f(res["set_estimate"][r])
        else:
            qs = q["quantiles"] or _DEFAULT_QS
            out["quantiles"] = {str(float(v)):
                                self._f(res["histo_quantiles"][r, qcol[v]])
                                for v in qs}
            out["median"] = self._f(res["histo_median"][r])
            out["min"] = self._f(res["histo_min"][r])
            out["max"] = self._f(res["histo_max"][r])
            out["count"] = self._f(res["histo_count"][r])
            out["sum"] = self._f(res["histo_sum"][r])
            out["avg"] = self._f(res["histo_avg"][r])
            out["hmean"] = self._f(res["histo_hmean"][r])
        return out

    def _render_range_entry(self, item, qi: int, q: dict, rinfo, rres,
                            rqcol) -> dict:
        if rinfo is None:
            return {"matches": [], "range": True}
        rows, truncated, soff, steps = rinfo["per_q"][(id(item), qi)]
        matches = [self._render_range(k, r, kind, name, jt, q, rres,
                                      rqcol, steps, soff)
                   for k, r, kind, name, jt in rows]
        entry = {"matches": matches, "range": True,
                 "interval_s": self._history.interval_s}
        if truncated:
            entry["truncated"] = True
        return entry

    def _render_range(self, k: int, r: int, kind: str, name: str,
                      jt: str, q: dict, rres, rqcol, steps,
                      soff: int) -> dict:
        """One range match -> its point series, OLDEST first. Counters
        add per-point rate (value over the step's wall span); scalar
        kinds add delta vs the previous rendered point — the
        'rates, deltas, sliding-window p99s' surface of the tier."""
        out = {"name": name, "kind": kind,
               "tags": jt.split(",") if jt else []}
        iv = self._history.interval_s
        pts = []
        for j, stp in enumerate(steps):
            s = soff + j
            p = {"ts": stp.ts_hi, "ts_start": stp.ts_lo,
                 "seq": [stp.seq_lo, stp.seq_hi],
                 "complete": bool(stp.complete)}
            if k == 0:
                v = self._f(rres["counter"][r, s])
                p["value"] = v
                span = max(stp.seq_hi - stp.seq_lo + 1, 1) * iv
                p["rate"] = (v / span) if v is not None else None
            elif k == 1:
                p["value"] = self._f(rres["gauge"][r, s])
            elif k == 2:
                p["value"] = self._f(rres["status"][r, s])
            elif k == 3:
                p["estimate"] = self._f(rres["set_estimate"][r, s])
            else:
                qs = q["quantiles"] or _DEFAULT_QS
                p["quantiles"] = {
                    str(float(v)):
                    self._f(rres["histo_quantiles"][r, s, rqcol[v]])
                    for v in qs}
                p["min"] = self._f(rres["histo_min"][r, s])
                p["max"] = self._f(rres["histo_max"][r, s])
                p["count"] = self._f(rres["histo_count"][r, s])
                p["sum"] = self._f(rres["histo_sum"][r, s])
            pts.append(p)
        pts.reverse()   # plan_range steps back from now; serve oldest->newest
        if k in (0, 1, 2):
            prev = None
            for p in pts:
                v = p.get("value")
                p["delta"] = (v - prev if v is not None
                              and prev is not None else None)
                prev = v
        out["points"] = pts
        return out
