"""Batching query engine: many concurrent reads, one device launch.

Dashboard reads arrive as independent HTTP requests; the engine
coalesces everything that shows up within a `query_timeout_ms` window
(capped at `query_max_batch` queries) into ONE pipeline snapshot and
ONE device launch. The launch IS the flush program —
`flush_live_in_packed`, the same jitted executable `compute_flush`
tiles over — fed with the union of the batch's quantile vectors and
the per-kind slot gathers the batch resolved. Running the identical
program on the identical captured state is what makes query answers
value-exact vs what the next flush would export:

- histogram/timer quantiles go through the Pallas quantile kernel on
  TPU (ops/pallas_digest.py) and the XLA vmap fallback on CPU, exactly
  as the flush does;
- HLL cardinalities come from the 6-bit packed i32 rows entirely on
  device (ops/hll.estimate_packed_rows — no host unpack);
- counters and histogram count/sum/recip scalars leave the device as
  two-float (hi, lo) pairs and are folded in float64 by
  combine_flush_scalars, the flush's own residual fold;
- live-interval set estimates are scaled by 2^active_set_shift here,
  mirroring the latched-shift correction server._do_flush applies.

Sharded backends flatten their [replica, shard, rows] state views with
free reshapes (global slot = shard·per_shard + local IS the flat
index), so a gather touches only the owner shard's rows; a
collective-attached tier with >1 replicas runs its ICI register-max
merge first so reads see the mesh-global sketches.

A batch takes TWO pipeline-queue visits (see query/snapshot.py for
the donation rationale): SnapshotRequest pins the interval's naming
view, the engine resolves names to slots off-thread, then a
PipelineCall dispatches `_launch` FROM the pipeline thread — enqueued
in FIFO order before any later donating ingest step, so the live
state buffers are still valid when the gather reads them. Only the
async dispatch (~µs) runs on the pipeline thread; compilation of the
query's bucket shape is a one-time cost per shape, and host
materialization, unpacking, and response assembly all happen on the
engine's own thread. An intervening swap() between the two visits is
detected by table identity and the batch retries against the fresh
interval, so a response never mixes two table versions.

The dispatch site is on the vtlint jax-hot-path/timer-sync scan
lists: launch cost is recorded under `dispatch_ns` (enqueue-only by
naming convention) and device completion is sampled through the ONE
sanctioned sync point, `observability/jaxruntime.sync_and_time`,
every `_SYNC_EVERY` launches — on the engine thread, never the
pipeline's.
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from veneur_tpu.observability import jaxruntime
from veneur_tpu.query.nameindex import NameIndex
from veneur_tpu.query.snapshot import (COUNT_TABLES, PipelineCall,
                                       SnapshotRequest)

log = logging.getLogger("veneur_tpu.query")

_SYNC_EVERY = 64            # sampled device-sync cadence (1 in N launches)
_SNAPSHOT_TIMEOUT_S = 30.0  # pipeline round-trip budget (CPU CI: a
                            # flush storm can hold the queue for seconds)
_SUBMIT_TIMEOUT_S = 30.0    # end-to-end budget an HTTP thread waits
_MAX_MATCHES = 1024         # per-query resolution cap (truncated flag set)
_MAX_QUANTILES = 64         # per-query quantile-vector cap

KINDS = ("counter", "gauge", "status", "set", "histogram", "timer")
_KIND_TABLE = {"counter": "counter", "gauge": "gauge", "status": "status",
               "set": "set", "histogram": "histo", "timer": "histo"}
_DEFAULT_QS = (0.5, 0.9, 0.99)


class QueryError(ValueError):
    """Client error in a /query request body (HTTP 400)."""


class _IntervalRolled(Exception):
    """swap() ran between the naming snapshot and the launch visit;
    the batch retries against the fresh interval."""


def _parse_one(q) -> dict:
    if not isinstance(q, dict):
        raise QueryError("each query must be a JSON object")
    modes = [k for k in ("name", "prefix", "match") if k in q]
    if len(modes) != 1:
        raise QueryError(
            "each query needs exactly one of name/prefix/match")
    mode = modes[0]
    arg = q[mode]
    if not isinstance(arg, str):
        raise QueryError(f"{mode} must be a string")
    kinds = q.get("kinds")
    if kinds is None and "kind" in q:
        kinds = [q["kind"]]
    if kinds is not None:
        if (not isinstance(kinds, (list, tuple)) or not kinds
                or any(k not in KINDS for k in kinds)):
            raise QueryError(f"kind(s) must be drawn from {KINDS}")
        kinds = tuple(kinds)
    qs = q.get("quantiles")
    if qs is not None:
        if not isinstance(qs, (list, tuple)) or not qs \
                or len(qs) > _MAX_QUANTILES:
            raise QueryError(
                f"quantiles must be a list of 1..{_MAX_QUANTILES} floats")
        try:
            qs = tuple(sorted({float(v) for v in qs}))
        except (TypeError, ValueError):
            raise QueryError("quantiles must be numbers")
        if any(not (0.0 <= v <= 1.0) for v in qs):
            raise QueryError("quantiles must lie in [0, 1]")
    tags = q.get("tags")
    if tags is not None:
        if not isinstance(tags, (list, tuple)) \
                or any(not isinstance(t, str) for t in tags):
            raise QueryError("tags must be a list of strings")
        tags = tuple(tags)
    return {"mode": mode, "arg": arg, "kinds": kinds,
            "quantiles": qs, "tags": tags}


def parse_request(body, max_queries: int) -> List[dict]:
    """POST /query body -> validated query list. Accepts
    {"queries": [...]} or a single bare query object."""
    if isinstance(body, dict) and "queries" in body:
        raw = body["queries"]
        if not isinstance(raw, list):
            raise QueryError("queries must be a list")
    elif isinstance(body, dict) and body:
        raw = [body]
    else:
        raise QueryError("empty query request")
    if not raw:
        raise QueryError("empty query request")
    if len(raw) > max_queries:
        raise QueryError(f"too many queries in one request "
                         f"(max {max_queries})")
    return [_parse_one(q) for q in raw]


class _Item:
    """One HTTP request's parsed queries + its completion slot."""

    __slots__ = ("queries", "done", "result", "error")

    def __init__(self, queries: List[dict]) -> None:
        self.queries = queries
        self.done = threading.Event()
        self.result: Optional[dict] = None
        self.error: Optional[Exception] = None


class QueryEngine:
    """Leader thread that batches, snapshots, launches and assembles."""

    def __init__(self, server, *, max_batch: int = 64,
                 timeout_ms: float = 2.0, requests=None, batched=None,
                 duration=None, stale_reads=None) -> None:
        self._server = server
        self.spec = server.aggregator.spec           # TOTAL capacities
        self.max_batch = max(1, int(max_batch))
        self.timeout_s = max(0.0, float(timeout_ms)) / 1000.0
        self._c_requests = requests
        self._c_batched = batched
        self._t_duration = duration
        self._c_stale_reads = stale_reads
        self._queue: "queue_mod.Queue[Optional[_Item]]" = queue_mod.Queue()
        self._stop = threading.Event()
        self._sync = jaxruntime.SampledSync(_SYNC_EVERY)
        self.dispatch_ns = 0
        self.launches_total = 0
        # one name index per (table identity, counts): a dashboard
        # polling the same interval pays the sort once
        self._index: Optional[NameIndex] = None
        self._index_key: Optional[tuple] = None
        self._index_table = None
        self._thread = threading.Thread(
            target=self._serve_loop, name="query-batcher", daemon=True)
        self._thread.start()

    # -- public API ----------------------------------------------------------
    def submit(self, body, timeout: float = _SUBMIT_TIMEOUT_S) -> dict:
        """Parse, join the current batch, wait for the leader. Raises
        QueryError (400) on a bad body, TimeoutError/RuntimeError (503)
        when the pipeline or device cannot serve."""
        queries = parse_request(body, self.max_batch)
        if self._c_requests is not None:
            self._c_requests.inc(len(queries))
        if self._stop.is_set():
            raise RuntimeError("query engine stopped")
        item = _Item(queries)
        self._queue.put(item)
        if not item.done.wait(timeout):
            raise TimeoutError("query timed out")
        if item.error is not None:
            raise item.error
        assert item.result is not None
        return item.result

    def close(self) -> None:
        self._stop.set()
        self._queue.put(None)
        self._thread.join(timeout=5.0)
        # wake anything still parked (shutdown race)
        while True:
            try:
                it = self._queue.get_nowait()
            except queue_mod.Empty:
                break
            if it is not None:
                it.error = RuntimeError("query engine stopped")
                it.done.set()

    # -- batching loop -------------------------------------------------------
    def _serve_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                if self._stop.is_set():
                    return
                continue
            batch = [item]
            total = len(item.queries)
            deadline = time.monotonic() + self.timeout_s
            while total < self.max_batch:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=rem)
                except queue_mod.Empty:
                    break
                if nxt is None:
                    break
                batch.append(nxt)
                total += len(nxt.queries)
            try:
                self._execute(batch, total)
            except Exception as e:  # noqa: BLE001 — waiters must wake
                log.exception("query batch failed")
                for it in batch:
                    if not it.done.is_set():
                        it.error = e
                        it.done.set()
            if self._stop.is_set():
                return

    # -- snapshot + index ----------------------------------------------------
    def _snapshot(self):
        req = SnapshotRequest()
        try:
            self._server.packet_queue.put(req, timeout=1.0)
        except queue_mod.Full:
            raise RuntimeError("pipeline backlogged; snapshot not scheduled")
        if not req.wait(_SNAPSHOT_TIMEOUT_S):
            raise RuntimeError("snapshot timed out")
        if not req.ok:
            raise RuntimeError(req.detail or "snapshot failed")
        return req.snapshot

    def _index_for(self, snap) -> NameIndex:
        key = (id(snap.table),
               tuple(snap.counts[t] for t in COUNT_TABLES))
        if self._index is not None and self._index_key == key:
            return self._index
        idx = NameIndex(snap.metas, snap.counts)
        # hold the table reference so the id() cache key stays unique
        self._index, self._index_key, self._index_table = idx, key, snap.table
        return idx

    # -- resolution ----------------------------------------------------------
    def _resolve(self, index: NameIndex, q: dict) -> List[tuple]:
        if q["kinds"] is not None:
            tables = list(dict.fromkeys(
                _KIND_TABLE[k] for k in q["kinds"]))
        else:
            tables = list(COUNT_TABLES)
        out = []
        for tname in tables:
            if q["mode"] == "name":
                ent = index.exact(tname, q["arg"])
            elif q["mode"] == "prefix":
                ent = index.prefix(tname, q["arg"])
            else:
                ent = index.match(tname, q["arg"])
            for pos, slot, meta in ent:
                if q["kinds"] is not None and tname == "histo" \
                        and meta.kind not in q["kinds"]:
                    continue
                if q["tags"] is not None \
                        and tuple(meta.tags) != q["tags"]:
                    continue
                out.append((tname, pos, slot, meta))
        return out

    # -- device launch -------------------------------------------------------
    def _launch(self, state, packed_inputs, n_q: int, buckets: tuple):
        """The query tier's ONE device dispatch (vtlint jax-hot-path +
        timer-sync covered): enqueue cost lands in dispatch_ns; the
        sampled completion sync runs later on the ENGINE thread."""
        from veneur_tpu.aggregation.step import flush_live_in_packed
        t0 = time.perf_counter_ns()
        out = flush_live_in_packed(state, packed_inputs, spec=self.spec,
                                   n_q=n_q, buckets=buckets)
        self.dispatch_ns += time.perf_counter_ns() - t0
        self.launches_total += 1
        return out

    def _launch_on_pipeline(self, aggregator, table, packed_inputs,
                            n_q: int, buckets: tuple):
        """Visit #2 body, pipeline-thread-only: re-drain staging,
        verify the interval the slots were resolved against is still
        live (swap() installs a fresh table object), and dispatch the
        gather while the state buffers are guaranteed undonated.
        Returns (device output, live set_shift)."""
        if aggregator.table is not table:
            raise _IntervalRolled()
        state, _table, set_shift = aggregator.query_snapshot()
        flat = aggregator.query_flat_state(state)
        return self._launch(flat, packed_inputs, n_q, buckets), \
            int(set_shift)

    # -- batch execution -----------------------------------------------------
    def _execute(self, batch: List[_Item], total: int) -> None:
        t0 = time.perf_counter_ns()
        plans = res = None
        qcol: dict = {}
        set_shift = 0
        for _attempt in range(2):
            try:
                plans, res, qcol, set_shift = self._plan_and_evaluate(batch)
                break
            except _IntervalRolled:
                # swap() landed between the two pipeline visits: the
                # resolved slots belong to the detached interval.
                # Retry once against the fresh table, then escalate
                continue
        else:
            # a flush storm keeps landing swaps inside the two-visit
            # window (manual trigger_flush loops; a timer interval
            # can't): fall back to ONE atomic pipeline visit that
            # snapshots, resolves and dispatches with no gap to roll
            # into. Costs index/resolution time on the pipeline thread,
            # so it is the escalation path, never the default.
            plans, res, qcol, set_shift = self._evaluate_atomic(batch)
        dur = time.perf_counter_ns() - t0
        # stale-bounded availability during a live reshard: the serving
        # table answers before all moved rows folded, so rows in flight
        # may be missing for at most one flush interval. The answer is
        # still served (availability wins); it is MARKED so consumers
        # and the chaos drill can pin the guarantee.
        stale = bool(getattr(self._server, "reshard_active", False))
        if stale and self._c_stale_reads is not None:
            self._c_stale_reads.inc(len(batch))
        for item, per_q in plans:
            results = []
            for rows, truncated, q in per_q:
                matches = [self._render(tname, r, meta, q, res, qcol)
                           for tname, r, meta in rows]
                entry = {"matches": matches}
                if truncated:
                    entry["truncated"] = True
                results.append(entry)
            item.result = {"results": results, "batched": total,
                           "set_shift": set_shift}
            if stale:
                item.result["stale_bounded"] = True
            if self._t_duration is not None:
                self._t_duration.observe(dur)
            item.done.set()

    def _plan(self, index: NameIndex, batch: List[_Item]):
        """Resolve every query in the batch against one name index:
        per-item render plans, the deduped per-table slot gathers, and
        the union quantile vector."""
        need: Dict[str, List[int]] = {t: [] for t in COUNT_TABLES}
        rowof: Dict[Tuple[str, int], int] = {}
        plans = []   # [(item, [(rows, truncated, q), ...])]
        union_qs = set()
        for item in batch:
            per_q = []
            for q in item.queries:
                ms = self._resolve(index, q)
                truncated = len(ms) > _MAX_MATCHES
                if truncated:
                    ms = ms[:_MAX_MATCHES]
                rows = []
                histo_hit = False
                for tname, pos, slot, meta in ms:
                    key = (tname, pos)
                    r = rowof.get(key)
                    if r is None:
                        r = len(need[tname])
                        rowof[key] = r
                        need[tname].append(slot)
                    rows.append((tname, r, meta))
                    histo_hit = histo_hit or tname == "histo"
                if histo_hit:
                    union_qs.update(q["quantiles"] or _DEFAULT_QS)
                per_q.append((rows, truncated, q))
            plans.append((item, per_q))
        return plans, need, union_qs

    def _build_inputs(self, need, union_qs):
        """Slot gathers + union quantiles -> the flush program's packed
        input buffer and static shape arguments (layout knowledge lives
        with the flush program in aggregation/step.py)."""
        from veneur_tpu.aggregation.step import pack_query_inputs
        return pack_query_inputs(
            self.spec, [need[t] for t in COUNT_TABLES], union_qs)

    def _materialize(self, packed, n_q, buckets, set_shift):
        """ENGINE-thread finish: sampled device sync, host transfer,
        unpack, residual fold, live set-shift correction."""
        from veneur_tpu.aggregation.step import (combine_flush_scalars,
                                                 flush_live_shapes,
                                                 unpack_flush)
        self._sync.tick(packed)
        out = unpack_flush(
            np.asarray(packed),
            flush_live_shapes(self.spec, *buckets, n_q))
        if self._c_batched is not None:
            self._c_batched.inc()
        res = combine_flush_scalars(out)
        # live-interval set estimates: the degrade ladder's sampling
        # shift has not been latched yet, so apply 2^active_set_shift
        # here — the same correction server._do_flush applies post-swap
        if set_shift:
            res = dict(res)
            res["set_estimate"] = (res["set_estimate"]
                                   * float(1 << set_shift))
        return res

    def _plan_and_evaluate(self, batch: List[_Item]):
        """Two-visit default: snapshot + off-thread resolution, then a
        pipeline-dispatched launch (if anything matched)."""
        snap = self._snapshot()
        index = self._index_for(snap)
        plans, need, union_qs = self._plan(index, batch)
        if not any(need[t] for t in COUNT_TABLES):
            return plans, None, {}, snap.set_shift
        inputs, n_q, buckets, qcol = self._build_inputs(need, union_qs)
        call = PipelineCall(lambda agg: self._launch_on_pipeline(
            agg, snap.table, inputs, n_q, buckets))
        self._pipeline_put(call)
        if not call.wait(_SNAPSHOT_TIMEOUT_S):
            raise RuntimeError("query launch timed out")
        if not call.ok:
            if isinstance(call.exc, _IntervalRolled):
                raise call.exc
            raise RuntimeError(call.detail or "query launch failed")
        packed, set_shift = call.result
        res = self._materialize(packed, n_q, buckets, set_shift)
        return plans, res, qcol, set_shift

    def _evaluate_atomic(self, batch: List[_Item]):
        """Escalation path: snapshot, resolution, and launch dispatch
        in ONE pipeline visit — immune to interval rolls because swap()
        runs on the same thread and cannot interleave."""
        from veneur_tpu.query.snapshot import _META_KIND, QuerySnapshot

        def fn(agg):
            state, table, set_shift = agg.query_snapshot()
            metas = {t: table.get_meta(_META_KIND[t])
                     for t in COUNT_TABLES}
            counts = {t: len(metas[t]) for t in COUNT_TABLES}
            snap = QuerySnapshot(table=table, metas=metas, counts=counts,
                                 set_shift=int(set_shift))
            index = self._index_for(snap)
            plans, need, union_qs = self._plan(index, batch)
            if not any(need[t] for t in COUNT_TABLES):
                return plans, None, None, snap.set_shift
            inputs, n_q, buckets, qcol = self._build_inputs(
                need, union_qs)
            flat = agg.query_flat_state(state)
            packed = self._launch(flat, inputs, n_q, buckets)
            return plans, packed, (n_q, buckets, qcol), snap.set_shift

        call = PipelineCall(fn)
        self._pipeline_put(call)
        if not call.wait(_SNAPSHOT_TIMEOUT_S):
            raise RuntimeError("query launch timed out")
        if not call.ok:
            raise RuntimeError(call.detail or "query launch failed")
        plans, packed, shape, set_shift = call.result
        if packed is None:
            return plans, None, {}, set_shift
        n_q, buckets, qcol = shape
        res = self._materialize(packed, n_q, buckets, set_shift)
        return plans, res, qcol, set_shift

    def _pipeline_put(self, item) -> None:
        try:
            self._server.packet_queue.put(item, timeout=1.0)
        except queue_mod.Full:
            raise RuntimeError("pipeline backlogged; query not scheduled")

    # -- response assembly ---------------------------------------------------
    @staticmethod
    def _f(v):
        v = float(v)
        return v if np.isfinite(v) else None

    def _render(self, tname: str, r: int, meta, q: dict, res, qcol) -> dict:
        out = {"name": meta.name, "kind": meta.kind,
               "tags": list(meta.tags)}
        if tname == "counter":
            out["value"] = self._f(res["counter"][r])
        elif tname == "gauge":
            out["value"] = self._f(res["gauge"][r])
        elif tname == "status":
            out["value"] = self._f(res["status"][r])
            out["message"] = getattr(meta, "message", "") or ""
        elif tname == "set":
            out["estimate"] = self._f(res["set_estimate"][r])
        else:
            qs = q["quantiles"] or _DEFAULT_QS
            out["quantiles"] = {str(float(v)):
                                self._f(res["histo_quantiles"][r, qcol[v]])
                                for v in qs}
            out["median"] = self._f(res["histo_median"][r])
            out["min"] = self._f(res["histo_min"][r])
            out["max"] = self._f(res["histo_max"][r])
            out["count"] = self._f(res["histo_count"][r])
            out["sum"] = self._f(res["histo_sum"][r])
            out["avg"] = self._f(res["histo_avg"][r])
            out["hmean"] = self._f(res["histo_hmean"][r])
        return out
