"""Consistent read snapshots of the live aggregation interval.

The snapshot discipline piggybacks on the double-buffer swap's
single-writer rule: everything that mutates live state — ingest
batches, HLL import staging, and swap() itself — runs on the ONE
pipeline thread, dispatched in packet-queue FIFO order. Query-tier
requests are just more queue items, which gives the tier:

- **Read-your-writes.** A sample admitted to the packet queue before
  the query's snapshot request is processed first (FIFO, one consumer)
  and therefore folded into the state the query reads. (The native
  ring path pumps rings each dispatch-loop iteration before draining
  the queue, so ring samples get the same guarantee up to one loop
  iteration.)
- **No torn reads across the swap.** swap() runs on the same thread: a
  pipeline request executes either entirely before it or entirely
  after it. The engine detects an intervening swap between its two
  visits by table identity (swap() installs a fresh key table) and
  retries, so a response never mixes two intervals.
- **Coherent name prefixes.** The key table is append-only within an
  interval, so per-kind meta COUNTS captured on the pipeline thread
  pin a prefix that is valid for the rest of the interval: resolution
  against that prefix can run off-thread against the captured meta
  list references (CPython list append is atomic) with no lock.

Why TWO pipeline visits instead of one captured state reference: the
ingest step DONATES its state buffers (`ingest_step*` alias input to
output), so a `jax.Array` captured mid-interval is invalidated —
"Array has been deleted" — by the very next ingest dispatch. JAX
immutability does not survive donation. The device gather therefore
has to be *enqueued from the pipeline thread* (SnapshotRequest #1
pins the name prefix, the engine resolves slots off-thread, then a
PipelineCall dispatches the flush-program launch in FIFO order before
any later donating step). The launch's output buffer is fresh — the
engine materializes it at leisure on its own thread.

`set_shift` is captured from the aggregator's live degrade ladder
(`active_set_shift`) because the 2^shift set-estimate correction that
server._do_flush applies from the LATCHED shift has not happened yet
for a live interval — the query engine applies it itself.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List

# canonical per-table count keys; "histo" covers histogram AND timer
# metas (they share a table — SlotMeta.kind tells them apart)
COUNT_TABLES = ("counter", "gauge", "status", "set", "histo")

# KeyTable.get_meta argument per count table
_META_KIND = {"counter": "counter", "gauge": "gauge", "status": "status",
              "set": "set", "histo": "histogram"}


@dataclass
class QuerySnapshot:
    """One coherent naming view of the live interval: the key table,
    per-kind meta list REFERENCES with the prefix lengths that were
    current on the pipeline thread, and the live set_shift. Carries no
    device state — see the module docstring for why (donation)."""
    table: Any
    metas: Dict[str, List[tuple]]
    counts: Dict[str, int]
    set_shift: int = 0


class PipelineRequest:
    """Base for packet-queue items the pipeline thread executes in
    FIFO order — the query tier's FlushRequest analogue. The waiter
    blocks on `done`; `finish(False, ...)` is the dispatch backstop's
    hook so an internal error never strands an HTTP thread."""

    __slots__ = ("done", "ok", "detail")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.ok = False
        self.detail = ""

    def run(self, aggregator) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def finish(self, ok: bool, detail: str = "") -> None:
        self.ok = ok
        self.detail = detail
        self.done.set()

    def wait(self, timeout: float | None = None) -> bool:
        return self.done.wait(timeout)


class SnapshotRequest(PipelineRequest):
    """Visit #1: drain staging and pin the interval's naming view."""

    __slots__ = ("snapshot",)

    def __init__(self) -> None:
        super().__init__()
        self.snapshot: QuerySnapshot | None = None

    def run(self, aggregator) -> None:
        """Pipeline-thread-only: drain staging, capture references."""
        try:
            _state, table, set_shift = aggregator.query_snapshot()
            # meta lists + counts are read HERE, on the pipeline
            # thread, so the prefix is exactly the drained state's key
            # population (on native tables get_meta also drains the
            # C++ key records, which is only safe from this thread
            # mid-interval). The list objects are append-only within
            # the interval — holding references lets the engine slice
            # `[:count]` later without another get_meta call.
            metas = {t: table.get_meta(_META_KIND[t])
                     for t in COUNT_TABLES}
            counts = {t: len(metas[t]) for t in COUNT_TABLES}
            self.snapshot = QuerySnapshot(table=table, metas=metas,
                                          counts=counts,
                                          set_shift=int(set_shift))
            self.ok = True
        except Exception as e:  # noqa: BLE001 — waiter must always wake
            self.detail = f"snapshot failed: {e}"
        finally:
            self.done.set()


class PipelineCall(PipelineRequest):
    """Visit #2 (and any future pipeline-thread errand): run `fn` on
    the pipeline thread, in FIFO order with ingest and swap, and hand
    its return value back. The query engine uses this to DISPATCH the
    device gather before any later donating ingest step can invalidate
    the live state buffers."""

    __slots__ = ("fn", "result", "exc")

    def __init__(self, fn: Callable[[Any], Any]) -> None:
        super().__init__()
        self.fn = fn
        self.result: Any = None
        self.exc: Exception | None = None

    def run(self, aggregator) -> None:
        try:
            self.result = self.fn(aggregator)
            self.ok = True
        except Exception as e:  # noqa: BLE001 — waiter must always wake
            self.exc = e
            self.detail = f"pipeline call failed: {e}"
        finally:
            self.done.set()
