"""Sorted name index over a snapshot's key-table prefix.

Resolution never touches the ingest hot path: the index is built
lazily on the query worker thread from the meta list references the
snapshot pinned on the pipeline thread (append-only within an
interval and CPython list append is atomic, so slicing `[:count]`
off-thread is safe while the pipeline keeps appending — and no
`get_meta` call happens off-thread, which matters on native tables
where get_meta drains the C++ key records). The engine caches one
index per (table identity, counts) so a dashboard polling the same
interval pays the sort once.

Three lookup modes per kind table:

- exact: all tag variants of one metric name (bisect on the sorted
  name column),
- prefix: every name in `[prefix, prefix+∞)` — a bisect range scan,
- match: `fnmatch`-style wildcard; the literal prefix before the
  first metacharacter narrows the scan range, then fnmatch filters.

Entries come back as (position, slot, meta) where `position` is the
row's index in the snapshot's meta-list prefix — the same positional
contract the flush output arrays follow — and `slot` is the global
device-table slot used for the gather.
"""

from __future__ import annotations

import bisect
from fnmatch import fnmatchcase
from typing import Dict, List, Tuple

from veneur_tpu.query.snapshot import COUNT_TABLES

_WILD = frozenset("*?[")


def literal_prefix(pattern: str) -> str:
    """The leading run of a wildcard pattern with no metacharacters."""
    for i, ch in enumerate(pattern):
        if ch in _WILD:
            return pattern[:i]
    return pattern


class NameIndex:
    """Per-kind sorted (name, position, slot, meta) columns."""

    def __init__(self, metas_by_table: Dict[str, list],
                 counts: Dict[str, int]) -> None:
        self._tables: Dict[str, Tuple[List[str], List[tuple]]] = {}
        for tname in COUNT_TABLES:
            n = counts.get(tname, 0)
            metas = metas_by_table[tname][:n]
            entries = sorted(
                ((m.name, pos, slot, m)
                 for pos, (slot, m) in enumerate(metas)),
                key=lambda e: e[0])
            self._tables[tname] = ([e[0] for e in entries], entries)

    def _span(self, tname: str, lo: str, hi: str) -> List[tuple]:
        names, entries = self._tables[tname]
        a = bisect.bisect_left(names, lo)
        b = bisect.bisect_left(names, hi) if hi is not None else len(names)
        return entries[a:b]

    def exact(self, tname: str, name: str) -> List[tuple]:
        """All tag variants of `name` -> [(position, slot, meta)]."""
        return [e[1:] for e in self._span(tname, name, name + "\0")]

    def prefix(self, tname: str, prefix: str) -> List[tuple]:
        hi = prefix + "\U0010ffff" if prefix else None
        return [e[1:] for e in self._span(tname, prefix, hi)]

    def match(self, tname: str, pattern: str) -> List[tuple]:
        lit = literal_prefix(pattern)
        hi = lit + "\U0010ffff" if lit else None
        return [e[1:] for e in self._span(tname, lit, hi)
                if fnmatchcase(e[0], pattern)]
