"""On-device query tier: serve live percentiles, cardinalities and
counters straight from resident device state (ROADMAP item 3 — the read
side of the metrics store).

The write path exports once per flush interval; dashboards read many
times in between. This package answers those reads from the SAME state
the next flush will export, with zero flush-path interference:

- `snapshot` — the consistent read-snapshot discipline. Query-tier
  requests ride the pipeline's packet queue (FIFO with ingest and
  FlushRequest): a SnapshotRequest pins a coherent
  (table-prefix, set_shift) naming view between batches, and a
  PipelineCall later dispatches the device gather from the pipeline
  thread itself — before any donating ingest step can invalidate the
  live state buffers. Read-your-writes holds for anything admitted to
  the queue before the query's snapshot; torn reads across the
  double-buffer swap are impossible by construction (an intervening
  swap is detected by table identity and the batch retries).
- `nameindex` — sorted-name resolution (exact / prefix / wildcard)
  over a snapshot's key-table prefix, built lazily on the query worker
  thread — never on the ingest hot path.
- `engine` — the batching engine: concurrent HTTP queries coalesce
  into ONE snapshot and ONE device launch through the exact flush
  program (`flush_live_in_packed`), which is what makes query answers
  value-exact vs the flush path on every backend.
"""

from veneur_tpu.query.engine import QueryEngine, QueryError, parse_request
from veneur_tpu.query.snapshot import (PipelineCall, PipelineRequest,
                                       QuerySnapshot, SnapshotRequest)

__all__ = ["PipelineCall", "PipelineRequest", "QueryEngine", "QueryError",
           "QuerySnapshot", "SnapshotRequest", "parse_request"]
