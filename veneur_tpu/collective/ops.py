"""Reusable named-axis merge collectives for sketch state.

The cross-replica merge that `parallel/sharded.py` runs at flush time is
a composition of five independent sketch merges, each tied to a metric
family's algebra (SURVEY §3.4; t-digests arxiv 1902.04023, HLL register
merge arxiv 2005.13332):

- two-float pair totals for counters and digest scalars (`psum` would
  round the ~48-bit pairs back to 24 bits, so it is an all-gather +
  error-free TwoSum fold),
- unpack → register max → `pmax` → repack for 6-bit packed HLL,
- stamp-argmax last-write-wins for gauges/status,
- all-gather + re-compress for t-digest centroids,
- `pmin`/`pmax` for histogram extremes.

This module generalizes them out of the sharded backend into functions
parameterized by the collective axis name, so the collective global tier
(collective/tier.py) and any future mesh program merge over whichever
axis carries replica-tier state. Every function expects the shard_map
block layout: a leading local-replica dim (the collapsed-mesh tile dim)
followed by [s_local, ...] table dims, and reduces BOTH the local dim
and the named axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from veneur_tpu.aggregation.state import DeviceState, TableSpec
from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.ops import tdigest as td

REPLICA_AXIS = "replica"
SHARD_AXIS = "shard"

# jax.shard_map went public after 0.4.x; older installs only have the
# experimental location
try:
    shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map


def twofloat_axis_sum(hi, lo, acc, axis: str = REPLICA_AXIS):
    """Sum two-float pairs across the local leading dim AND `axis`
    without collapsing to f32 (a plain psum of hi+lo rounds the ~48-bit
    pairs back to 24 bits — the same boundary bug combine_flush_scalars
    fixes on the host). Gather every participant's pair and fold
    sequentially with error-free TwoSum merges; the global counter merge
    then matches the reference's exact int64 adds (importsrv ->
    Counter.Merge)."""
    from veneur_tpu.utils.numerics import twofloat_add, twofloat_merge
    hi, lo = twofloat_add(hi, lo, acc)   # absorb any unfolded acc
    hs = jax.lax.all_gather(hi, axis)    # [Rg, r_local, s, K]
    ls = jax.lax.all_gather(lo, axis)
    hs = hs.reshape((-1,) + hs.shape[2:])
    ls = ls.reshape((-1,) + ls.shape[2:])

    def body(carry, x):
        return twofloat_merge(carry[0], carry[1], x[0], x[1]), None

    (h, l), _ = jax.lax.scan(body, (hs[0], ls[0]), (hs[1:], ls[1:]))
    return h, l


def hll_axis_max(packed, axis: str = REPLICA_AXIS, *, precision: int):
    """Register-wise HLL union across the local leading dim and `axis`
    (reference Set.Merge, samplers/samplers.go:461). The resident layout
    is 6-bit packed i32 words; componentwise max of packed WORDS is not
    register max (a high register field dominates the word compare
    regardless of the low fields), so unpack to dense u8 registers, max
    locally and across the collective, repack. The dense form is
    transient — it never lands in state or HBM-resident buffers."""
    dense = hll_ops.unpack_registers(packed, precision=precision)
    dense = jax.lax.pmax(dense.max(axis=0), axis)
    return hll_ops.pack_registers(dense, precision=precision)


def lww_axis_merge(val, stamp, axis: str = REPLICA_AXIS):
    """Last-write-wins merge with canonical order = highest global
    participant index that wrote (reference Gauge.Merge overwrites,
    :297). Returns (merged values, written-mask u8)."""
    r_local = val.shape[0]
    ridx = jax.lax.axis_index(axis) * r_local + jnp.arange(r_local)
    ridx = ridx.reshape((r_local,) + (1,) * (val.ndim - 1))
    prio = jnp.where(stamp > 0, ridx + 1, 0)
    vals = jax.lax.all_gather(val, axis)          # [Rg, r_local, s, K]
    prios = jax.lax.all_gather(prio, axis)
    vals = vals.reshape((-1,) + vals.shape[2:])
    prios = prios.reshape((-1,) + prios.shape[2:])
    win = jnp.argmax(prios, axis=0)
    merged = jnp.take_along_axis(vals, win[None], axis=0)[0]
    written = prios.max(axis=0) > 0
    return merged, written.astype(jnp.uint8)


def digest_axis_merge(wm, w, axis: str = REPLICA_AXIS, *,
                      spec: TableSpec):
    """t-digest merge: gather every participant's centroids for the key,
    concatenate along the centroid axis, re-compress to canonical cells
    (the fixed-shape analogue of Histo.Merge digest re-add,
    samplers/samplers.go:726). Returns (h_wm, h_w) in the state's
    [C + temp] column layout with the temp cells emptied."""
    wm = jax.lax.all_gather(wm, axis)   # [Rg, r_local, s, K, C]
    w = jax.lax.all_gather(w, axis)
    wm = jnp.moveaxis(wm.reshape((-1,) + wm.shape[2:]), 0, -2)  # [s,K,R,C]
    w = jnp.moveaxis(w.reshape((-1,) + w.shape[2:]), 0, -2)
    s_l, k, r, c = w.shape
    mean = wm / jnp.maximum(w, 1e-30)
    mean = mean.reshape(s_l, k, r * c)
    w = w.reshape(s_l, k, r * c)
    m2, w2 = td.compress_rows(mean, w, compression=spec.compression,
                              cells_per_k=spec.cells_per_k,
                              out_c=spec.centroids,
                              exact_extremes=spec.exact_extremes)
    pad = jnp.zeros(w2.shape[:-1] + (spec.temp_cells,), w2.dtype)
    w2 = jnp.concatenate([w2, pad], axis=-1)
    wm2 = jnp.concatenate([m2 * w2[..., :spec.centroids], pad], axis=-1)
    return wm2, w2


def extremes_axis_merge(h_min, h_max, axis: str = REPLICA_AXIS):
    return (jax.lax.pmin(h_min.min(axis=0), axis),
            jax.lax.pmax(h_max.max(axis=0), axis))


def merge_replica_block(state: DeviceState, spec: TableSpec,
                        axis: str = REPLICA_AXIS) -> DeviceState:
    """Inside shard_map: merge a [r_local, s_local, ...] block over the
    full `axis` (local reduce + named-axis collective). Returns arrays
    with the replica dims reduced away — one merged table per shard
    tile."""
    counters = twofloat_axis_sum(state.counter_hi, state.counter_lo,
                                 state.counter_acc, axis)
    h_count = twofloat_axis_sum(state.h_count_hi, state.h_count_lo,
                                state.h_count_acc, axis)
    h_sum = twofloat_axis_sum(state.h_sum_hi, state.h_sum_lo,
                              state.h_sum_acc, axis)
    h_recip = twofloat_axis_sum(state.h_recip_hi, state.h_recip_lo,
                                state.h_recip_acc, axis)

    hll = hll_axis_max(state.hll, axis, precision=spec.hll_precision)

    gauge, gauge_stamp = lww_axis_merge(state.gauge, state.gauge_stamp,
                                        axis)
    status, status_stamp = lww_axis_merge(state.status,
                                          state.status_stamp, axis)

    wm2, w2 = digest_axis_merge(state.h_wm, state.h_w, axis, spec=spec)
    h_min, h_max = extremes_axis_merge(state.h_min, state.h_max, axis)

    z = jnp.zeros_like
    return DeviceState(
        counter_acc=z(counters[0]), counter_hi=counters[0],
        counter_lo=counters[1],
        gauge=gauge, gauge_stamp=gauge_stamp,
        status=status, status_stamp=status_stamp,
        hll=hll,
        h_wm=wm2, h_w=w2,
        h_temp_n=jnp.zeros(w2.shape[:-1], jnp.int32),
        h_min=h_min, h_max=h_max,
        h_count_acc=z(h_count[0]), h_count_hi=h_count[0],
        h_count_lo=h_count[1],
        h_sum_acc=z(h_sum[0]), h_sum_hi=h_sum[0], h_sum_lo=h_sum[1],
        h_recip_acc=z(h_recip[0]), h_recip_hi=h_recip[0],
        h_recip_lo=h_recip[1],
    )
