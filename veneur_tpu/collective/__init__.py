"""Collective global tier: hash-routed mesh key table + ICI sketch
merge, zero-serialization co-located forward.

- ops.py      named-axis sketch-merge collectives (generalized out of
              parallel/sharded.py)
- keytable.py deterministic hash-routed key table (route by key
              identity, owner assigns slots)
- router.py   all_to_all routed ingest + replica-merged-state programs
- tier.py     CollectiveGlobalTier server backend + process-local
              tier registry
"""

from veneur_tpu.collective.keytable import (
    CollectiveKeyTable, route_digest, route_shard)
from veneur_tpu.collective.ops import (
    REPLICA_AXIS, SHARD_AXIS, digest_axis_merge, extremes_axis_merge,
    hll_axis_max, lww_axis_merge, merge_replica_block, twofloat_axis_sum)

__all__ = [
    "REPLICA_AXIS", "SHARD_AXIS", "CollectiveKeyTable", "route_digest",
    "route_shard", "merge_replica_block", "twofloat_axis_sum",
    "hll_axis_max", "lww_axis_merge", "digest_axis_merge",
    "extremes_axis_merge",
]
