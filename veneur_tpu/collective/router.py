"""Mesh programs for the collective global tier.

make_routed_ingest is the zero-serialization delivery path: a co-located
local tier's flush rows are staged host-side into per-(replica, source
shard, DEST shard) buckets, shipped to the mesh as one Batch with
leading [R, S_src, S_dest] dims, and routed to their owner shards by an
on-device `lax.all_to_all` over the shard axis INSIDE shard_map — after
which each owner tile applies its rows with the exact same ingest
scatter the local tiers use. No protobuf, no gRPC, no host round-trip:
the merge payload crosses the interconnect as device arrays.

make_merged_state runs the replica-axis sketch merge alone (no flush
math), producing one merged [S, ...] DeviceState for the raw checkpoint/
forward gather.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P

from veneur_tpu.aggregation.state import DeviceState, TableSpec
from veneur_tpu.aggregation.step import ingest_core
from veneur_tpu.collective.ops import (
    REPLICA_AXIS, SHARD_AXIS, merge_replica_block, shard_map)


def shard_axis_is_physical(mesh: Mesh, n_shards: int) -> bool:
    """all_to_all routing needs the logical shard axis fully laid out on
    devices (one owner tile per shard); on collapsed fallback meshes the
    tier falls back to host-side owner bucketing, which is semantically
    identical (rows still land on their owner's scatter)."""
    return mesh.shape[SHARD_AXIS] == n_shards


def make_routed_ingest(mesh: Mesh, spec: TableSpec):
    """Jitted (state, batch) -> state. `batch` lanes carry leading
    [R, S_src, S_dest, B] dims: dim 1 is mesh placement (which shard
    column the rows start on), dim 2 the owner shard the stager routed
    each bucket to. Inside shard_map each tile all_to_alls dim 2 over
    the shard axis — turning it into a source index — then flattens the
    arriving buckets into one row batch for the owner's ingest scatter.

    Requires shard_axis_is_physical(mesh, n_shards) (tile dim 1 must be
    size 1 so dim 2 lines up with the physical axis)."""
    core = partial(ingest_core, spec=spec, allow_pallas=False)

    def block(state, batch):
        def route(x):
            # [r_l, 1, S_dest, B, ...] -> dest becomes source after the
            # exchange; fold sources into one flat row axis
            y = jax.lax.all_to_all(x, SHARD_AXIS, split_axis=2,
                                   concat_axis=2)
            return y.reshape(y.shape[:2] + (-1,) + y.shape[4:])

        routed = jax.tree.map(route, batch)
        return jax.vmap(jax.vmap(core))(state, routed)

    fn = shard_map(
        block, mesh=mesh,
        in_specs=(P(REPLICA_AXIS, SHARD_AXIS), P(REPLICA_AXIS, SHARD_AXIS)),
        out_specs=P(REPLICA_AXIS, SHARD_AXIS))
    return jax.jit(fn, donate_argnums=(0,))


def make_merged_state(mesh: Mesh, spec: TableSpec):
    """Jitted state[R,S,...] -> replica-merged DeviceState with leading
    [S] dim — the raw-gather twin of make_merged_flush (same
    merge_replica_block, no flush math)."""

    def block(state: DeviceState):
        return merge_replica_block(state, spec, REPLICA_AXIS)

    # replica-reduced outputs aren't replicated the way the checker
    # wants; the kwarg that disables the check was renamed
    # check_rep -> check_vma
    try:
        fn = shard_map(block, mesh=mesh,
                       in_specs=(P(REPLICA_AXIS, SHARD_AXIS),),
                       out_specs=P(SHARD_AXIS), check_vma=False)
    except TypeError:
        fn = shard_map(block, mesh=mesh,
                       in_specs=(P(REPLICA_AXIS, SHARD_AXIS),),
                       out_specs=P(SHARD_AXIS), check_rep=False)
    return jax.jit(fn)
