"""CollectiveGlobalTier: the global aggregation tier as a mesh resident.

A ShardedAggregator whose mesh carries a real replica axis: co-located
local tiers hand their flush's raw sketch arrays straight to
`absorb_raw` (zero serialization — no protobuf, no gRPC, no wire
bytes), rows are staged into per-(replica row, source column, OWNER
shard) buckets using the hash-routed CollectiveKeyTable, and one
on-device `all_to_all` inside shard_map delivers every bucket to its
owner tile where the ordinary ingest scatter applies it
(collective/router.py). Flush time replica-merges the mesh with the
same named-axis sketch collectives the sharded backend uses
(collective/ops.py) — the 64-process gRPC merge becomes one collective
program over ICI.

The envelope/gRPC forward path stays authoritative for cross-host (DCN)
peers: a local tier with a dialed forward client keeps using it;
`collective_attach` only short-circuits the co-located case.

Participant rows spread over replica rows round-robin (participant p ->
replica p % R, staging column (p // R) % S), so N locals' absorbs
parallelize over the replica axis instead of serializing into row 0.
Absorb payloads are EXACTLY what the wire path would deliver —
iter_forwardable (forward/convert.py) is shared with export_metrics —
with one documented exception: HLL rows skip the axiomhq nibble
serialization, so where that format's tailcut would saturate a register
spread > 15 the absorbed union is lossless (strictly more accurate, and
byte-identical whenever the spread fits, i.e. in practice).
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

from veneur_tpu.aggregation.host import Batcher, BatchSpec
from veneur_tpu.aggregation.state import TableSpec
from veneur_tpu.aggregation.step import Batch
from veneur_tpu.collective.keytable import CollectiveKeyTable
from veneur_tpu.observability import jaxruntime
from veneur_tpu.observability.registry import Timer
from veneur_tpu.server.aggregator import _SYNC_EVERY
from veneur_tpu.server.sharded_aggregator import (
    ShardedAggregator, per_shard_spec)

# -- process-local tier registry -------------------------------------------
# Co-located servers living in one process (the deployment shape the
# collective tier exists for) find each other here; lookup by group name
# at flush time so start order does not matter.
_REGISTRY: Dict[str, "CollectiveGlobalTier"] = {}
_REGISTRY_LOCK = threading.Lock()


def register(group: str, tier: "CollectiveGlobalTier") -> None:
    with _REGISTRY_LOCK:
        _REGISTRY[group] = tier


def lookup(group: str) -> Optional["CollectiveGlobalTier"]:
    with _REGISTRY_LOCK:
        return _REGISTRY.get(group)


def unregister(group: str, tier: "CollectiveGlobalTier") -> None:
    with _REGISTRY_LOCK:
        if _REGISTRY.get(group) is tier:
            del _REGISTRY[group]


class CollectiveGlobalTier(ShardedAggregator):
    def __init__(self, spec: TableSpec, bspec: BatchSpec = BatchSpec(),
                 n_shards: int = 2, n_replicas: int = 1,
                 compact_every: int = 8):
        import jax  # noqa: F401  (device availability surfaces early)
        from veneur_tpu.aggregation.step import batch_sizes
        from veneur_tpu.collective.router import (
            make_merged_state, make_routed_ingest, shard_axis_is_physical)
        from veneur_tpu.parallel import (
            make_mesh, make_merged_flush, make_sharded_ingest_packed,
            sharded_empty_state)

        self.spec = spec
        self.pspec = per_shard_spec(spec, n_shards)
        self.bspec = bspec
        self.n_shards = n_shards
        self.n_replicas = max(1, int(n_replicas))
        self.compact_every = compact_every

        self.mesh = make_mesh(self.n_replicas, n_shards)
        self._sizes = batch_sizes(Batcher(self.pspec, bspec).force_emit())
        self._ingest = make_sharded_ingest_packed(self.mesh, self.pspec,
                                                  self._sizes)
        self._flush = make_merged_flush(self.mesh, self.pspec)
        self._merge = make_merged_state(self.mesh, self.pspec)
        self._empty = partial(sharded_empty_state, self.pspec,
                              self.n_replicas, n_shards, self.mesh)
        self.state = self._empty()
        self.table = CollectiveKeyTable(spec, n_shards)
        # direct traffic (process_metric / import_metric / restore)
        # stages into replica row 0 through the inherited batchers
        self.batchers = self._make_batchers()
        # absorb staging: one Batcher per (replica row, source column,
        # owner shard); the routed all_to_all delivers buckets to owners
        self._route_device = shard_axis_is_physical(self.mesh, n_shards)
        self._routed = (make_routed_ingest(self.mesh, self.pspec)
                        if self._route_device else None)
        self._stage_grid = self._make_stage_grid()
        self._absorb_lock = threading.Lock()
        self._next_participant = 0
        self._routed_steps = 0
        self.absorbed_rows = 0
        self._hll_slots: List[Tuple[int, int]] = []
        self._hll_rows: List[np.ndarray] = []
        self._restore_residuals: list = []
        self._steps = 0
        self.processed = 0
        self.dropped_capacity = 0
        self.h2d_bytes = 0
        self.step_ns = 0
        self.dispatch_ns = 0
        self.steps_total = 0
        self.steps_synced = 0
        # always-on phase timers: a private Timer instance until a host
        # server injects its registry-owned one (set_phase_timer), so
        # phase durations accumulate with or without a Server around.
        # Phases: stage (absorb_raw host staging), all_to_all_route
        # (routed dispatch), replica_merge / flush (compute_flush).
        self._phase_timer = Timer(
            "veneur.collective.phase_duration_ns",
            help="collective tier phase wall time by phase (ns)",
            labelnames=("phase",))
        # cross-tier tracing: the last absorb's (trace_id, span_id) so
        # compute_flush's replica_merge span parents onto it, closing
        # the local->global span tree; the trace client rides along.
        self._last_absorb = None
        self._trace_client = None
        self._init_degrade()

    def set_phase_timer(self, timer) -> None:
        """Adopt a registry-owned phase-duration Timer (the host Server
        registers `veneur.collective.phase_duration_ns` and injects it
        here so phase observations reach its /metrics exposition)."""
        self._phase_timer = timer

    # -- absorb staging ------------------------------------------------------
    def _make_stage_grid(self):
        if not self._route_device:
            return None
        grid = []
        for r in range(self.n_replicas):
            row = []
            for j in range(self.n_shards):
                row.append([Batcher(self.pspec, self.bspec,
                                    on_batch=partial(self._on_stage_batch,
                                                     r, j, d))
                            for d in range(self.n_shards)])
            grid.append(row)
        return grid

    def _on_stage_batch(self, r: int, j: int, d: int, batch: Batch):
        """A stage bucket filled mid-absorb: emit the whole grid (the
        routed program is rectangular) with the filled bucket's batch in
        place, everyone else force-emitted — the _on_shard_batch pattern
        one level up."""
        self._dispatch_routed(
            lambda rr, jj, dd: batch if (rr, jj, dd) == (r, j, d)
            else self._stage_grid[rr][jj][dd].force_emit())

    def _dispatch_routed(self, get):
        nested = []
        for r in range(self.n_replicas):
            row = []
            for j in range(self.n_shards):
                dest = [get(r, j, d) for d in range(self.n_shards)]
                cols = list(zip(*dest))
                row.append(Batch(*[None if all(x is None for x in col)
                                   else np.stack(col) for col in cols]))
            nested.append(row)
        from veneur_tpu.parallel import stack_batches
        batch = stack_batches(nested, self.n_replicas, self.n_shards)
        self.h2d_bytes += sum(a.nbytes for a in batch if a is not None)
        t0 = time.perf_counter_ns()
        self.state = self._routed(self.state, batch)
        dispatch_dt = time.perf_counter_ns() - t0
        self.dispatch_ns += dispatch_dt
        self._phase_timer.observe(dispatch_dt, phase="all_to_all_route")
        self.steps_total += 1
        if self.steps_total % _SYNC_EVERY == 0:
            self.step_ns += dispatch_dt + jaxruntime.sync_and_time(
                self.state)
            self.steps_synced += 1
        # absorbed digest rows land in temp cells like any other ingest;
        # ride the packed program's in-band compact word at the same
        # cadence as direct traffic so they recompress
        self._routed_steps += 1
        if self._routed_steps % self.compact_every == 0:
            self._dispatch_row([b.force_emit() for b in self.batchers],
                               force_compact=True)

    def _emit_absorbed(self):
        if self._stage_grid is None:
            return
        if not any(b.pending() for row in self._stage_grid
                   for cell in row for b in cell):
            return
        self._dispatch_routed(
            lambda r, j, d: self._stage_grid[r][j][d].force_emit())

    # -- direct dispatch over the [R, S] mesh --------------------------------
    def _dispatch_row(self, row, force_compact: bool = False):
        """Direct-traffic twin of ShardedAggregator._dispatch_row for an
        R-row mesh: row 0 carries the packed shard batches, rows 1..R-1
        carry a constant all-padding packed row (absorbed traffic reaches
        them through the routed path instead)."""
        from veneur_tpu.aggregation.step import pack_batch, packed_layout
        self._steps += 1
        self.steps_total += 1
        dc = force_compact or (self._steps % self.compact_every == 0)
        bufs = getattr(self, "_row_bufs", None)
        if bufs is None:
            words = packed_layout(self._sizes)[1]
            pad = np.zeros(words, np.int32)
            pack_batch(Batcher(self.pspec, self.bspec).force_emit(),
                       False, out=pad)
            base = np.broadcast_to(
                pad, (self.n_replicas, self.n_shards, words)).copy()
            bufs = self._row_bufs = [base, base.copy(), 0]
        flat = bufs[bufs[2]]
        bufs[2] ^= 1
        for i, b in enumerate(row):
            pack_batch(b, dc, out=flat[0, i])
        self.h2d_bytes += flat.nbytes
        t0 = time.perf_counter_ns()
        self.state = self._ingest(self.state, flat)
        dispatch_dt = time.perf_counter_ns() - t0
        self.dispatch_ns += dispatch_dt
        if self.steps_total % _SYNC_EVERY == 0:
            self.step_ns += dispatch_dt + jaxruntime.sync_and_time(
                self.state)
            self.steps_synced += 1

    # -- zero-serialization absorb -------------------------------------------
    def assign_participant(self) -> int:
        """Claim a stable participant id (-> replica row / staging
        column) for a co-located local tier."""
        with self._absorb_lock:
            p = self._next_participant
            self._next_participant += 1
            return p

    def absorb_raw(self, raw, table, participant: Optional[int] = None,
                   parent_span=None, trace_client=None) -> int:
        """Fold a co-located local tier's flush output (raw arrays + its
        detached KeyTable) into the collective state. Returns the number
        of rows absorbed. Thread-safe against concurrent absorbs and the
        tier's own swap. With parent_span (the local's flush.forward
        span), emits a collective.absorb child span carrying rows/bytes
        tags — the same tree shape the wire path's import span produces
        — and remembers its ids so compute_flush's replica_merge span
        parents onto this absorb."""
        from veneur_tpu.forward.convert import iter_forwardable
        span = None
        if parent_span is not None:
            span = parent_span.child("collective.absorb")
            span.set_tag("transport", "colocated")
        with self._absorb_lock:
            if participant is None:
                participant = self._next_participant
                self._next_participant += 1
            r = participant % self.n_replicas
            j = (participant // self.n_replicas) % self.n_shards
            n = 0
            t0 = time.perf_counter_ns()
            for kind, meta, scope, payload in iter_forwardable(
                    raw, table, self.spec.hll_precision):
                self._absorb_one(r, j, kind, meta, scope, payload)
                n += 1
            self._phase_timer.observe(time.perf_counter_ns() - t0,
                                      phase="stage")
            self.absorbed_rows += n
            if span is not None:
                span.set_tag("rows", str(n))
                try:
                    span.set_tag("bytes", str(sum(
                        a.nbytes for a in raw.values()
                        if hasattr(a, "nbytes"))))
                except AttributeError:
                    pass
                self._last_absorb = (span.trace_id, span.id)
                self._trace_client = trace_client
                span.client_finish(trace_client)
            return n

    def _absorb_one(self, r: int, j: int, kind: str, meta, scope: int,
                    payload: dict) -> None:
        slot = self.table.slot_for_routed(
            kind, meta.name, meta.tags, scope, hostname=meta.hostname,
            imported=True, joined_tags=meta.joined_tags)
        if slot is None:
            self.dropped_capacity += 1
            return
        shard, local = self._local(kind, slot)
        if self._stage_grid is not None:
            b = self._stage_grid[r][j][shard]
        else:
            # collapsed fallback mesh: owner-bucket on the host straight
            # into the direct batchers (semantically identical delivery)
            b = self.batchers[shard]
        if kind == "counter":
            b.add_counter(local, float(payload["value"]), 1.0)
        elif kind == "gauge":
            b.add_gauge(local, float(payload["value"]))
        elif kind == "set":
            # imported register rows can't ride the Batch member lanes;
            # they merge through the established (shard, local) host
            # fold -> on-device register max (order-free), replica row 0
            regs = payload["registers"]
            if regs.shape[0] != self.pspec.registers:
                raise ValueError("absorbed HLL register-count mismatch")
            self._hll_slots.append((shard, local))
            self._hll_rows.append(regs)
        elif kind in ("histogram", "timer"):
            means = np.asarray(payload["means"], np.float32)
            weights = np.asarray(payload["weights"], np.float32)
            live = weights > 0
            means, weights = means[live], weights[live]
            b.add_histos_bulk(np.full(len(means), local, np.int32),
                              means, weights)
            recip = payload.get("recip")
            recip_corr = 0.0
            if recip is not None and np.all(means != 0.0):
                recip_corr = float(recip) - float(np.sum(weights / means))
            b.add_histo_stats(local, float(payload.get("min", np.inf)),
                              float(payload.get("max", -np.inf)),
                              recip_corr)
        self.processed += 1

    # -- flush ---------------------------------------------------------------
    def swap(self):
        with self._absorb_lock:
            self._emit_absorbed()
            if self._routed_steps and not self._steps:
                # absorb-only interval: the inherited swap's boundary
                # sync keys off _steps, which routed dispatch bypasses
                self.step_ns += jaxruntime.sync_and_time(self.state)
                self.steps_synced += 1
            state, table = super().swap()
            # super() installed a plain KeyTable; the collective tier
            # routes by key identity
            self.table = CollectiveKeyTable(self.spec, self.n_shards)
            self._stage_grid = self._make_stage_grid()
            self._routed_steps = 0
            return state, table

    # -- query tier ---------------------------------------------------------
    def query_snapshot(self):
        """Absorb-staged routed rows are part of 'admitted before the
        snapshot' too: fold them under the absorb lock (the same mutual
        exclusion swap() takes against forwarding threads), then
        snapshot as a sharded backend."""
        with self._absorb_lock:
            self._emit_absorbed()
            return super().query_snapshot()

    def query_flat_state(self, state):
        """R > 1: replica-merge the mesh first (the flush's own ICI
        collectives — register max for HLL, the mergeable reductions
        elsewhere) so reads see the mesh-global sketches, then flatten
        the shard axis like the sharded backend."""
        if self.n_replicas == 1:
            return super().query_flat_state(state)
        import jax
        merged = self._merge(state)
        return jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]),
                            merged)

    def compute_flush(self, state, table, percentiles,
                      want_raw: bool = False, history=None):
        t_flush = time.perf_counter_ns()
        try:
            return self._compute_flush_timed(state, table, percentiles,
                                             want_raw, history)
        finally:
            # implicitly synced: every return path host-materializes the
            # flush arrays (np.asarray), so this is true wall time
            # vtlint: disable=timer-sync -- callee's np.asarray is the sync
            self._phase_timer.observe(time.perf_counter_ns() - t_flush,
                                      phase="flush")

    def _compute_flush_timed(self, state, table, percentiles,
                             want_raw: bool = False, history=None):
        # the replica_merge span parents onto the most recent co-located
        # absorb and is emitted on EVERY flush path — on the plain path
        # the merge collectives run inside the compiled flush itself, so
        # the span covers the whole compute; either way the cross-tier
        # trace stays connected (local forward -> absorb -> merge)
        from veneur_tpu.trace.tracer import Span
        mspan = None
        if self._last_absorb is not None:
            tid, sid = self._last_absorb
            mspan = Span("collective.replica_merge", service="veneur",
                         trace_id=tid, parent_id=sid)
            mspan.set_tag("replicas", str(self.n_replicas))
        try:
            return self._compute_flush_inner(state, table, percentiles,
                                             want_raw, history)
        finally:
            if mspan is not None:
                mspan.client_finish(self._trace_client)
                self._last_absorb = None

    def _compute_flush_inner(self, state, table, percentiles,
                             want_raw: bool = False, history=None):
        if self.n_replicas == 1 or (not want_raw and history is None):
            # R == 1: the inherited raw gather reads the state verbatim,
            # byte-identical to the sharded backend by construction
            return super().compute_flush(state, table, percentiles,
                                         want_raw, history=history)
        import jax
        import jax.numpy as jnp
        from veneur_tpu.aggregation.step import live_indices, unpack_flush
        from veneur_tpu.server.sharded_aggregator import (
            _gather_sharded_raw, _sharded_raw_shapes)
        # R > 1: replica-merge the mesh first (same collectives as the
        # flush), then reuse the [1, S] raw gather on the merged state
        result, table = super().compute_flush(state, table, percentiles)
        setidx = jnp.asarray(
            live_indices(table, "set", self.spec.set_capacity))
        hidx = jnp.asarray(
            live_indices(table, "histogram", self.spec.histo_capacity))
        t0 = time.perf_counter_ns()
        merged = jax.tree.map(lambda x: x[None], self._merge(state))
        jaxruntime.sync_and_time(merged)
        merge_synced_dt = time.perf_counter_ns() - t0
        self._phase_timer.observe(merge_synced_dt, phase="replica_merge")
        r = unpack_flush(
            np.asarray(_gather_sharded_raw(merged, setidx, hidx)),
            _sharded_raw_shapes(self.pspec, len(setidx), len(hidx)))
        raw = {
            "counter": result["counter"],
            "gauge": result["gauge"],
            "hll": r["hll"],
            "h_mean": r["h_mean"],
            "h_weight": r["h_weight"],
            "h_min": r["h_min"],
            "h_max": r["h_max"],
            "h_recip": r["recip_hi"].astype(np.float64) + r["recip_lo"],
        }
        if history is not None:
            # replica-merged raw is the mesh-global frame — the one the
            # archive keeps — so the ring stores the same bytes a replay
            # of those frames would
            history.record_frame(table, result, raw)
        if want_raw:
            return result, table, raw
        return result, table
