"""Deterministic hash-routed key table for the collective global tier.

The base KeyTable routes a key to a shard from whatever digest the
caller hands it — the datagram parser, the protobuf importer and the
checkpoint restorer each hash differently, and the by_key dict makes the
FIRST arrival's digest decide placement. That arrival-order dependence
is exactly why cross-process state was never slot-aligned
(parallel/multihost.py header) and the global merge had to ride gRPC.

The collective tier instead derives the routing digest from the key
identity itself — fnv1a-32 over (name, kind, joined_tags), the restore
recipe — so every participant, in every process, across restarts,
computes the same owner shard for the same key with no coordination.
Slots WITHIN the owner shard are still assigned by the owner in arrival
order (the tier instance is the single slot authority), which is all
`all_to_all` routing needs: rows only have to land on the right device;
the owner's scatter indexes are its own.
"""

from __future__ import annotations

from veneur_tpu.aggregation.host import KeyTable
from veneur_tpu.utils.hashing import fnv1a_32


def route_digest(kind: str, name: str, joined_tags: str) -> int:
    """Routing digest over the key identity alone — same recipe as
    persistence/restore.py so restored and absorbed rows agree. The
    histogram/timer split matters for identity (they are distinct keys)
    but both live in the histo device table; the caller passes the
    actual kind."""
    h = fnv1a_32(name.encode("utf-8", "surrogateescape"))
    h = fnv1a_32(kind.encode(), h)
    return fnv1a_32(joined_tags.encode("utf-8", "surrogateescape"), h)


def route_shard(kind: str, name: str, joined_tags: str,
                n_shards: int) -> int:
    return route_digest(kind, name, joined_tags) % n_shards


class CollectiveKeyTable(KeyTable):
    """KeyTable whose shard routing is a pure function of key identity.

    slot_for_routed ignores the caller's digest and recomputes the
    routing digest from (kind, name, joined_tags); the inherited
    slot_for stays available for paths that already agree on digests
    (restore uses the identical recipe, so both land the same)."""

    def slot_for_routed(self, kind: str, name: str, tags, scope: int,
                        hostname: str = "", imported: bool = False,
                        joined_tags=None):
        if joined_tags is None:
            joined_tags = ",".join(tags)
        digest = route_digest(kind, name, joined_tags)
        return self.slot_for(kind, name, tags, scope, digest,
                             hostname=hostname, imported=imported,
                             joined_tags=joined_tags)

    def routing_signature(self) -> int:
        """Stable hash of the full (key -> owner shard) mapping, for
        asserting cross-restart routing determinism. Slot order within a
        shard is arrival-order and deliberately excluded."""
        per = {k: t.per_shard for k, t in self.tables.items()}
        items = []
        for kind, tbl in self.tables.items():
            for (k_kind, k_name, k_joined), slot in tbl.by_key.items():
                items.append((kind, k_kind, k_name, k_joined,
                              slot // per[kind]))
        h = fnv1a_32(b"route-sig")
        for item in sorted(items):
            for part in item[:4]:
                h = fnv1a_32(str(part).encode("utf-8",
                                              "surrogateescape"), h)
            h = fnv1a_32(str(item[4]).encode(), h)
        return h
