"""Sharded on-device aggregation core.

This package is the TPU-native replacement for the reference's worker layer
(reference worker.go: per-goroutine maps of samplers keyed by MetricKey).
Instead of hash-sharded goroutines with mutex-guarded maps, state is a set of
fixed-capacity device arrays ("the key table") updated by batched XLA scatter
ops under jit, and sharded across devices on the key axis with shard_map.

- state.py   — TableSpec + DeviceState (the arrays) + constructors
- step.py    — the jitted ingest step / fold / compact / flush computations
- host.py    — host-side key dictionary (name/type/tags -> slot) and batcher
"""

from veneur_tpu.aggregation.state import TableSpec, DeviceState, empty_state
from veneur_tpu.aggregation.step import (
    Batch, ingest_step, fold_scalars, compact, flush_compute)
from veneur_tpu.aggregation.host import KeyTable, Batcher

__all__ = [
    "TableSpec", "DeviceState", "empty_state", "Batch", "ingest_step",
    "fold_scalars", "compact", "flush_compute", "KeyTable", "Batcher",
]
