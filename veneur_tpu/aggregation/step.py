"""The jitted ingest step and flush computations.

This is the hot core replacing the reference's Worker.ProcessMetric switch
(reference worker.go:344) and the samplers' Sample methods
(reference samplers/samplers.go:142/225/375/484). One call processes a whole
padded batch of parsed samples of every type with a handful of scatter ops;
state is donated so updates are in-place on device.

Histogram ingestion is the interesting part. The reference buffers samples
into a temp array and runs a sequential greedy merge (reference
tdigest/merging_digest.go:115,140). Here every sample is assigned a k-cell
directly: its quantile midpoint is estimated from (a) the current digest's
mass below the sample value (a [B, C] gather + compare against the row's
centroids) and (b) the mass of earlier batch samples in the same key segment
(sort by (slot, value) + segmented cumsum). The sample's (weight, weight*value)
is then scatter-added into its (slot, cell). Cell assignments drift as the
distribution evolves, so the host periodically re-compresses rows
(``compact``), which re-bins all mass at once — the fixed-shape analogue of
the reference's amortized mergeAllTemps.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from veneur_tpu.aggregation.state import DeviceState, TableSpec
from veneur_tpu.ops import hll as hll_ops
from veneur_tpu.ops import tdigest as td
from veneur_tpu.utils.numerics import twofloat_add


class Batch(NamedTuple):
    """A padded batch of parsed samples. Padding rows carry slot == capacity
    (out of range) so their scatters drop. All arrays are fixed-size per
    configuration, so one compiled program serves every step."""
    counter_slot: jax.Array   # i32[Bc]
    counter_inc: jax.Array    # f32[Bc]  value * (1/sample_rate), reference samplers.go:142
    gauge_slot: jax.Array     # i32[Bg]
    gauge_val: jax.Array      # f32[Bg]
    status_slot: jax.Array    # i32[Bst]
    status_val: jax.Array     # f32[Bst]
    set_slot: jax.Array       # i32[Bs]
    set_reg: jax.Array        # i32[Bs]
    set_rho: jax.Array        # u8[Bs]
    histo_slot: jax.Array     # i32[Bh]
    histo_val: jax.Array      # f32[Bh]
    histo_wt: jax.Array       # f32[Bh]  1/sample_rate, reference samplers.go:484
    # import-side digest scalars (global tier merge, worker.go:438
    # ImportMetricGRPC): per imported digest, its exact min/max/reciprocalSum
    # ride these lanes instead of being lossily re-derived from centroids.
    # None on pure-ingest batches (the common case).
    histo_stat_slot: jax.Array = None   # i32[Bm]
    histo_stat_min: jax.Array = None    # f32[Bm]
    histo_stat_max: jax.Array = None    # f32[Bm]
    histo_stat_recip: jax.Array = None  # f32[Bm]


def _last_per_slot_set(target, stamp, slot, val, capacity):
    """Scatter-set the LAST batch value per slot (gauge semantics,
    reference samplers/samplers.go:225 last-write-wins) and mark the slot's
    write stamp."""
    idx = jnp.arange(slot.shape[0], dtype=jnp.int32)
    order = jnp.lexsort((idx, slot))
    s = slot[order]
    v = val[order]
    is_last = jnp.concatenate([s[:-1] != s[1:], jnp.ones((1,), bool)])
    tgt = jnp.where(is_last & (s >= 0) & (s < capacity), s, capacity)
    return (target.at[tgt].set(v, mode="drop"),
            stamp.at[tgt].set(jnp.uint8(1), mode="drop"))


def _histo_plan(state: DeviceState, slot, val, wt, spec: TableSpec):
    """The estimate/temp cell-assignment math of `_histo_update`, factored
    out so the fused Pallas ingest kernel (ops/pallas_ingest.py) consumes
    the EXACT same sorted streams the scatter chain does — byte parity by
    construction. Returns (s, cell, v, w, tadd): batch sorted by
    (slot, value) with invalid rows mapped to slot==histo_capacity, the
    target cell column per row, the value/weight streams, and the
    temp-slot consumption (0/1) per row."""
    c = spec.centroids
    t = spec.temp_cells
    kh = spec.histo_capacity
    valid = (slot >= 0) & (slot < kh) & (wt > 0)
    slot = jnp.where(valid, slot, kh)
    # sort batch by (slot, value) so each key's samples are a contiguous,
    # value-ordered segment
    order = jnp.lexsort((val, slot))
    s = slot[order]
    v = jnp.where(valid[order], val[order], 0.0)
    w = jnp.where(valid[order], wt[order], 0.0)
    ok = valid[order]

    # segment bookkeeping: start flags, ids, within-segment rank
    idx = jnp.arange(s.shape[0], dtype=jnp.int32)
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), s[1:] != s[:-1]])
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    rank = idx - jax.lax.cummax(jnp.where(seg_start, idx, 0))

    # T samples per compaction cycle land verbatim in a key's temp cells
    # (exact — no estimate involved), the fixed-shape analogue of the
    # reference digest's temp buffer (merging_digest.go:105-140). Temp
    # PRIORITY within each batch segment goes to the segment's most
    # EXTREME samples, alternating bottom/top (ext_order is a
    # permutation of 0..seg_len-1: bottom-0, top-0, bottom-1, top-1, …),
    # so when a hot key overflows temp, it's the MID-RANGE samples that
    # fall back to estimate-based k-cells — where cells are
    # statistically thick and merging is harmless — while the tail
    # samples that decide p99 stay raw until compaction's exact-extreme
    # protection (ops/tdigest.py) takes them over. First-come order
    # instead (the pre-r05 behavior) let tail samples of hot keys merge
    # in narrow estimate cells, the dominant per-key p99 error term.
    seg_cnt = jax.ops.segment_sum(
        jnp.where(ok, 1, 0).astype(jnp.int32), seg_id,
        num_segments=s.shape[0], indices_are_sorted=True)[seg_id]
    r_top = seg_cnt - 1 - rank
    ext_order = 2 * jnp.minimum(rank, r_top) + (rank > r_top)
    # Temp budget per batch: half of what's left (with a small floor),
    # so one big batch can't starve the rest of the compaction cycle —
    # every batch in the cycle keeps at least its ±8-rank neighborhood
    # of the tail queries exact. h_temp_n counts USED slots only (see
    # below), so unused budget rolls over to the next batch.
    avail = t - state.h_temp_n[jnp.minimum(s, kh - 1)]
    allowed = jnp.maximum(avail // 2, jnp.minimum(avail, 16))
    use_temp = ok & (ext_order < allowed)
    temp_idx = state.h_temp_n[jnp.minimum(s, kh - 1)] + ext_order

    # mass of the current digest below each sample value (temp cells
    # participate: their "means" are raw sample values)
    sc = jnp.minimum(s, kh - 1)
    row_w = state.h_w[sc]                     # f32[B, C+T]
    row_wm = state.h_wm[sc]
    row_mean = row_wm / jnp.maximum(row_w, 1e-30)
    w_main = jnp.sum(row_w, axis=-1)
    below = (jnp.sum(row_w * (row_mean < v[:, None]), axis=-1)
             + 0.5 * jnp.sum(row_w * (row_mean == v[:, None]), axis=-1))

    # mass of earlier batch samples in the same segment
    cum_excl = jnp.cumsum(w) - w
    base = jax.lax.cummax(jnp.where(seg_start, cum_excl, 0.0))
    cum_seg = cum_excl - base
    seg_tot = jax.ops.segment_sum(w, seg_id, num_segments=s.shape[0],
                                  indices_are_sorted=True)[seg_id]

    q_mid = (below + cum_seg + 0.5 * w) / jnp.maximum(w_main + seg_tot, 1e-30)
    k0 = -spec.compression / 4.0
    cell = jnp.floor((td._k1(q_mid, spec.compression) - k0)
                     * spec.cells_per_k).astype(jnp.int32)
    # estimate-based scatter lands in the k-cell INTERIOR only — the
    # protected extreme columns [0,E) and [C-E,C) are written exclusively
    # by compaction, which owns rank order (ops/tdigest.py compress_rows)
    cell = spec.exact_extremes + jnp.clip(cell, 0, spec.interior_cells - 1)
    cell = jnp.where(use_temp, c + jnp.minimum(temp_idx, t - 1), cell)
    return s, cell, v, w, jnp.where(use_temp, 1, 0).astype(jnp.int32)


def _histo_update(state: DeviceState, slot, val, wt, spec: TableSpec):
    s, cell, v, w, tadd = _histo_plan(state, slot, val, wt, spec)
    h_w = state.h_w.at[s, cell].add(w, mode="drop")
    h_wm = state.h_wm.at[s, cell].add(w * v, mode="drop")
    # count USED temp slots (samples that overflowed to estimate cells
    # don't consume budget — their slots stay available to later batches
    # in the cycle)
    h_temp_n = state.h_temp_n.at[s].add(tadd, mode="drop")
    h_min = state.h_min.at[s].min(jnp.where(w > 0, v, jnp.inf), mode="drop")
    h_max = state.h_max.at[s].max(jnp.where(w > 0, v, -jnp.inf), mode="drop")
    h_count = state.h_count_acc.at[s].add(w, mode="drop")
    h_sum = state.h_sum_acc.at[s].add(w * v, mode="drop")
    # Go float64 division by zero yields +Inf; match (harmonic mean of a
    # stream containing 0 is 0 downstream).
    h_recip = state.h_recip_acc.at[s].add(
        jnp.where(w > 0, w / v, 0.0), mode="drop")
    return state._replace(h_w=h_w, h_wm=h_wm, h_temp_n=h_temp_n,
                          h_min=h_min, h_max=h_max,
                          h_count_acc=h_count, h_sum_acc=h_sum,
                          h_recip_acc=h_recip)


def ingest_core(state: DeviceState, batch: Batch, *, spec: TableSpec,
                allow_pallas: bool = True) -> DeviceState:
    """Apply one padded batch to the table. The whole reference hot loop
    below the worker channel (reference server.go:984 -> worker.go:344 ->
    samplers Sample) becomes this one compiled program. Pure function —
    `ingest_step` is the donating jit wrapper; parallel/sharded.py wraps it
    in shard_map/vmap instead (with allow_pallas=False: the per-tile body
    runs under vmap, where the fused kernel's scalar-prefetch grid does
    not apply).

    When the fused Pallas ingest kernel is active (ops/pallas_ingest.py:
    probe-gated on TPU, `pallas_ingest_enabled` config / env force, byte
    parity pinned by tests/test_pallas_ingest.py), the scatter chain below
    is replaced by ONE kernel over VMEM-tiled state blocks; the XLA chain
    remains the portable fallback and the parity oracle."""
    from veneur_tpu.ops import pallas_ingest
    if allow_pallas and pallas_ingest.active():
        state = pallas_ingest.fused_ingest_core(
            state, batch, spec=spec,
            interpret=pallas_ingest.interpret_mode())
    else:
        counter_acc = state.counter_acc.at[batch.counter_slot].add(
            batch.counter_inc, mode="drop")
        gauge, gauge_stamp = _last_per_slot_set(
            state.gauge, state.gauge_stamp, batch.gauge_slot,
            batch.gauge_val, spec.gauge_capacity)
        status, status_stamp = _last_per_slot_set(
            state.status, state.status_stamp, batch.status_slot,
            batch.status_val, spec.status_capacity)
        hll = hll_ops.insert_batch_packed(
            state.hll, batch.set_slot, batch.set_reg, batch.set_rho,
            precision=spec.hll_precision)
        state = state._replace(counter_acc=counter_acc,
                               gauge=gauge, gauge_stamp=gauge_stamp,
                               status=status, status_stamp=status_stamp,
                               hll=hll)
        state = _histo_update(state, batch.histo_slot, batch.histo_val,
                              batch.histo_wt, spec)
    if batch.histo_stat_slot is not None:
        s = batch.histo_stat_slot
        state = state._replace(
            h_min=state.h_min.at[s].min(batch.histo_stat_min, mode="drop"),
            h_max=state.h_max.at[s].max(batch.histo_stat_max, mode="drop"),
            h_recip_acc=state.h_recip_acc.at[s].add(batch.histo_stat_recip,
                                                    mode="drop"))
    # Fold the batch's scatter accumulators into the two-float pairs
    # INSIDE the ingest program: XLA fuses the elementwise fold into the
    # scatter dispatch (no extra launch), the f32 accumulator never
    # carries more than one batch, and the pair absorbs each batch via
    # error-free TwoSum — so counters match the reference's int64 for
    # any realistic interval (e.g. a lone :1|c arriving after 2^32 no
    # longer rounds away, which a 64-batch fold cadence allowed).
    return _fold_core(state)


ingest_step = partial(jax.jit, static_argnames=("spec", "allow_pallas"),
                      donate_argnames=("state",))(ingest_core)


# -- packed batch transfer ---------------------------------------------------
# On a tunneled TPU every host->device array transfer pays a full sync RTT;
# a 16-lane Batch cost 16 RTTs per step and throttled real ingest to ~32k
# samples/s while the compute itself ran at >100M samples/s (measured).
# The fix mirrors the flush direction (flush_live_in_packed): ship the whole
# batch as ONE flat i32 buffer and rebuild the lanes with static slices +
# bitcasts inside the compiled program. i32 is the carrier because integer
# transfers are bit-exact (an f32 carrier could canonicalize NaN payloads
# in i32 lanes).

_U8_LANES = frozenset({"set_rho"})
_F32_LANES = frozenset({
    "counter_inc", "gauge_val", "status_val", "histo_val", "histo_wt",
    "histo_stat_min", "histo_stat_max", "histo_stat_recip"})


def batch_sizes(batch: Batch) -> tuple:
    """Static lane lengths of a batch (the packed program's compile key,
    alongside spec). None lanes (the optional histo_stat_* import-scalar
    lanes) encode as 0 and round-trip back to None."""
    return tuple(0 if a is None else int(a.size) for a in batch)


def packed_layout(sizes: tuple):
    """Word layout of the pack_batch buffer for the given lane sizes:
    ({lane_name: (word_off, n, words)}, total_words). Word 0 is the
    control word; lanes follow in Batch._fields order, u8 lanes padded
    to word multiples, 0-size (None) lanes absent. This is the one
    definition of the wire<->device layout — pack_batch writes it, the
    native engine's vt_emit_packed is handed these offsets, and
    unpack_batch walks the same order inside jit."""
    layout = {}
    off = 1
    for name, n in zip(Batch._fields, sizes):
        if n == 0:
            continue
        words = (n + 3) // 4 if name in _U8_LANES else n
        layout[name] = (off, n, words)
        off += words
    return layout, off


def pack_batch(batch: Batch, do_compact: bool = False, out=None):
    """Host side: one contiguous i32 buffer holding every lane (f32 lanes
    bit-viewed, u8 lanes padded to word multiples, None lanes skipped),
    preceded by one control word (the in-band compact flag — a separate
    scalar argument would be a second transfer). Each lane is written
    straight into its packed_layout slice — no intermediate parts list or
    concatenation — so hot-path callers pass a persistent zero-initialized
    `out` (aggregator.py double-buffers two; sharded packs into rows of
    one [1, S, W] array) and the pack costs one pass with zero
    allocations. Without `out` a fresh zeroed buffer is returned. A
    reused `out` must have been zero-initialized once at allocation: u8
    pad bytes are never rewritten, and every non-pad word is overwritten
    on every pack, so the buffer stays bit-identical to a fresh pack."""
    import numpy as np
    layout, words = packed_layout(batch_sizes(batch))
    if out is None:
        out = np.zeros(words, np.int32)
    out[0] = 1 if do_compact else 0
    for name, a in zip(Batch._fields, batch):
        if a is None:
            continue
        off, n, w = layout[name]
        if name in _U8_LANES:
            out[off:off + w].view(np.uint8)[:n] = a
        elif name in _F32_LANES:
            out[off:off + n].view(np.float32)[:] = a
        else:
            out[off:off + n] = a
    return out


def unpack_batch(flat, sizes: tuple) -> Batch:
    """Device side (inside jit): static slices + bitcasts back into lanes.
    A 0 size restores the lane to None (ingest_core's optional-lane
    contract, see Batch docstring)."""
    out = []
    off = 0
    for name, n in zip(Batch._fields, sizes):
        if n == 0:
            out.append(None)
            continue
        if name in _U8_LANES:
            words = (n + 3) // 4
            a = jax.lax.bitcast_convert_type(
                flat[off:off + words], jnp.uint8).reshape(-1)[:n]
            off += words
        elif name in _F32_LANES:
            a = jax.lax.bitcast_convert_type(flat[off:off + n], jnp.float32)
            off += n
        else:
            a = flat[off:off + n]
            off += n
        out.append(a)
    return Batch(*out)


def packed_step_core(state: DeviceState, flat, *, spec: TableSpec,
                     sizes: tuple) -> DeviceState:
    """The un-jitted production step: ingest one packed batch; when the
    control word is set, re-compress the digest rows in the SAME program
    (lax.cond — only the taken branch executes). Folding compaction in
    keeps the steady-state hot loop at ONE resident executable, which
    matters twice: fewer dispatches is plain good TPU practice, and the
    tunneled single-chip backend drops to a slow per-dispatch mode once
    more than two distinct executables are in flight (measured:
    2s/dispatch for a separate compact program). Shared by
    ingest_step_packed and the driver entry (__graft_entry__.entry)."""
    state = ingest_core(state, unpack_batch(flat[1:], sizes), spec=spec)
    return jax.lax.cond(flat[0] != 0,
                        lambda s: compact_core(s, spec=spec),
                        lambda s: s, state)


ingest_step_packed = partial(
    jax.jit, static_argnames=("spec", "sizes"),
    donate_argnames=("state",))(packed_step_core)


def packed_rings_core(state: DeviceState, arena, *, spec: TableSpec,
                      sizes: tuple) -> DeviceState:
    """Multi-ring step: `arena` is i32[R, words] — one packed row per
    reader ring, all shipped in ONE host->device transfer (the multi-ring
    pipeline's whole point: R rings cost one RTT, not R). The loop is
    unrolled at trace time (R is static via the arena shape), so XLA sees
    R back-to-back packed steps in a single program — same executable
    residency story as ingest_step_packed, and the fused Pallas ingest
    kernel (when active inside ingest_core) runs per row against its
    scalar-prefetch windows unchanged. Idle rings ride as sentinel-only
    rows whose scatters all drop; the host skips the step entirely when
    every ring emitted zero rows. Only row 0 carries the compact control
    word — one compaction per step, exactly like the single-ring path."""
    n_rings = arena.shape[0]
    state = packed_step_core(state, arena[0], spec=spec, sizes=sizes)
    for r in range(1, n_rings):
        state = ingest_core(state, unpack_batch(arena[r][1:], sizes),
                            spec=spec)
    return state


ingest_step_packed_rings = partial(
    jax.jit, static_argnames=("spec", "sizes"),
    donate_argnames=("state",))(packed_rings_core)


def _fold_core(state: DeviceState) -> DeviceState:
    ch, cl = twofloat_add(state.counter_hi, state.counter_lo, state.counter_acc)
    hch, hcl = twofloat_add(state.h_count_hi, state.h_count_lo, state.h_count_acc)
    hsh, hsl = twofloat_add(state.h_sum_hi, state.h_sum_lo, state.h_sum_acc)
    hrh, hrl = twofloat_add(state.h_recip_hi, state.h_recip_lo, state.h_recip_acc)
    z = jnp.zeros_like
    return state._replace(
        counter_acc=z(state.counter_acc), counter_hi=ch, counter_lo=cl,
        h_count_acc=z(state.h_count_acc), h_count_hi=hch, h_count_lo=hcl,
        h_sum_acc=z(state.h_sum_acc), h_sum_hi=hsh, h_sum_lo=hsl,
        h_recip_acc=z(state.h_recip_acc), h_recip_hi=hrh, h_recip_lo=hrl)


# Standalone fold kept for flush-time finalization (a last partial batch
# staged through non-ingest paths) and the host fold cadence, which is now
# a harmless no-op on already-folded state.
fold_scalars = jax.jit(_fold_core)


def compact_core(state: DeviceState, *, spec: TableSpec) -> DeviceState:
    """Re-compress every digest row — canonical k-cells AND raw temp cells —
    into canonical k-cells, emptying temp. Amortized analogue of the
    reference's mergeAllTemps (merging_digest.go:140)."""
    mean = state.h_wm / jnp.maximum(state.h_w, 1e-30)
    m2, w2 = td.compress_rows(mean, state.h_w, compression=spec.compression,
                              cells_per_k=spec.cells_per_k,
                              out_c=spec.centroids,
                              exact_extremes=spec.exact_extremes)
    pad = jnp.zeros(w2.shape[:-1] + (spec.temp_cells,), w2.dtype)
    return state._replace(
        h_wm=jnp.concatenate([m2 * w2, pad], axis=-1),
        h_w=jnp.concatenate([w2, pad], axis=-1),
        h_temp_n=jnp.zeros_like(state.h_temp_n))


compact = partial(jax.jit, static_argnames=("spec",),
                  donate_argnames=("state",))(compact_core)


def quantiles_with_median(table, qs):
    """ONE quantile pass for (requested quantiles, median): the median
    rides as an extra column instead of a second full per-row sort+cumsum
    over the digest table — the flush program's dominant compute, which
    XLA does not reliably CSE. Returns (quantiles[..., Q], median[...])."""
    all_q = td.quantiles(
        table, jnp.concatenate([qs, jnp.asarray([0.5], jnp.float32)]))
    return all_q[..., :-1], all_q[..., -1]


def flush_core(state: DeviceState, qs: jax.Array, *, spec: TableSpec):
    """Produce the final per-slot values the flusher turns into InterMetrics
    (reference flusher.go:225 generateInterMetrics), dense over capacity.
    No fold/compact prerequisite: ingest folds accumulators in-program and
    the quantile kernel argsorts cells per row, so unmerged temp cells are
    just extra exact centroids. The production path uses the live-slot
    variants below; this dense form serves kernels/benchmarks/tests."""
    mean = state.h_wm / jnp.maximum(state.h_w, 1e-30)
    table = td.TDigestTable(
        mean=mean, weight=state.h_w, min=state.h_min, max=state.h_max,
        count_hi=state.h_count_hi, count_lo=state.h_count_lo,
        sum_hi=state.h_sum_hi, sum_lo=state.h_sum_lo,
        recip_hi=state.h_recip_hi, recip_lo=state.h_recip_lo)
    # Scalar totals leave the device as UNCOLLAPSED two-float pairs:
    # hi + lo in f32 would round the ~48-bit accumulator back to 24 bits
    # at the very boundary the pair exists to protect (a 2^32+1 counter
    # interval would flush as 2^32). The host combines them in float64
    # (combine_flush_scalars) — device f64 is unavailable without
    # jax_enable_x64.
    hq, hmed = quantiles_with_median(table, qs)
    return {
        "counter_hi": state.counter_hi,
        "counter_lo": state.counter_lo,
        "gauge": state.gauge,
        "status": state.status,
        "set_estimate": hll_ops.estimate(state.hll,
                                         precision=spec.hll_precision),
        "histo_quantiles": hq,
        "histo_min": state.h_min,
        "histo_max": state.h_max,
        "histo_count_hi": state.h_count_hi,
        "histo_count_lo": state.h_count_lo,
        "histo_sum_hi": state.h_sum_hi,
        "histo_sum_lo": state.h_sum_lo,
        "histo_recip_hi": state.h_recip_hi,
        "histo_recip_lo": state.h_recip_lo,
        "histo_median": hmed,
    }


flush_compute = partial(jax.jit, static_argnames=("spec",))(flush_core)


def _take(a, idx):
    return jnp.take(a, idx, axis=0, mode="clip")


def flush_live_core(state: DeviceState, qs: jax.Array, cidx, gidx, stidx,
                    setidx, hidx, *, spec: TableSpec, want_raw: bool = False):
    """flush_core restricted to LIVE slots: gather each kind's occupied
    rows (idx arrays padded to a size bucket) before any flush math, so
    (a) the quantile/estimate compute runs on O(live) rows instead of
    O(capacity), and (b) only O(live) bytes cross the device→host
    boundary — on a tunneled TPU the dense transfer dominated the whole
    flush (~4s per interval at 2^17 capacity). Output arrays are indexed
    by POSITION: row i corresponds to table.get_meta(kind)[i]."""
    wm = _take(state.h_wm, hidx)
    w = _take(state.h_w, hidx)
    mn = _take(state.h_min, hidx)
    mx = _take(state.h_max, hidx)
    chi, clo = _take(state.h_count_hi, hidx), _take(state.h_count_lo, hidx)
    shi, slo = _take(state.h_sum_hi, hidx), _take(state.h_sum_lo, hidx)
    rhi, rlo = _take(state.h_recip_hi, hidx), _take(state.h_recip_lo, hidx)
    mean = wm / jnp.maximum(w, 1e-30)
    table = td.TDigestTable(
        mean=mean, weight=w, min=mn, max=mx,
        count_hi=chi, count_lo=clo, sum_hi=shi, sum_lo=slo,
        recip_hi=rhi, recip_lo=rlo)
    hll_rows = _take(state.hll, setidx)
    hq, hmed = quantiles_with_median(table, qs)
    out = {
        "counter_hi": _take(state.counter_hi, cidx),
        "counter_lo": _take(state.counter_lo, cidx),
        "gauge": _take(state.gauge, gidx),
        "status": _take(state.status, stidx),
        "set_estimate": hll_ops.estimate(hll_rows,
                                         precision=spec.hll_precision),
        "histo_quantiles": hq,
        "histo_min": mn,
        "histo_max": mx,
        "histo_count_hi": chi, "histo_count_lo": clo,
        "histo_sum_hi": shi, "histo_sum_lo": slo,
        "histo_recip_hi": rhi, "histo_recip_lo": rlo,
        "histo_median": hmed,
    }
    if want_raw:
        # forwarding needs the mergeable sketch state of live rows
        out["raw_hll"] = hll_rows
        out["raw_h_mean"] = mean
        out["raw_h_weight"] = w
    return out


def _pack_outputs(out: dict):
    parts = []
    for k in sorted(out):
        a = out[k]
        if a.dtype == jnp.uint8:
            a = jax.lax.bitcast_convert_type(a.reshape((-1, 4)),
                                             jnp.float32)
        elif a.dtype == jnp.int32:
            # packed HLL rows (raw_hll) ride the f32 carrier bit-cast.
            # Safe: a 6-bit register never exceeds 64-p+1 <= 61, so the
            # longest run of set bits across packed field boundaries is 5
            # — an f32 NaN/Inf needs 8 consecutive exponent ones, which
            # the carrier therefore can never form (no canonicalization
            # hazard on the way back to the host).
            a = jax.lax.bitcast_convert_type(a, jnp.float32)
        parts.append(a.reshape(-1).astype(jnp.float32))
    return jnp.concatenate(parts)


def pack_flush_inputs(perc, idx_arrays):
    """Host side: quantile list + the five live-index buckets as ONE i32
    buffer (f32 quantiles bit-viewed), the H2D mirror of the packed
    output — 6 transfers per flush become 1."""
    import numpy as np
    qs = np.asarray(perc, np.float32).view(np.int32)
    return np.concatenate([qs] + [np.asarray(i, np.int32).ravel()
                                  for i in idx_arrays])


def pack_query_inputs(spec, need, union_qs):
    """Host side: the query tier's gather plan -> the flush program's
    packed input buffer + static shape args (n_q, buckets, qcol).

    Same wire layout as `pack_flush_inputs`, but shaped for ad-hoc
    reads instead of a full-table flush: quantiles pad to the next
    power of two (min 4) so arbitrary per-query quantile vectors hit a
    handful of `flush_live_in_packed` specializations instead of
    recompiling per distinct count, and each kind's slot gather pads
    with `pad_bucket` exactly like the flush tiling — which is what
    keeps query reads running the flush's own jitted program (and
    therefore value-exact against the next flush's exports).

    `need` maps table name -> live slot list in flush-table order
    (counter, gauge, status, set, histo); `union_qs` is the batch's
    union quantile set. Returns (inputs, n_q, buckets, qcol) where
    qcol maps quantile value -> column in the padded vector.
    """
    import numpy as np
    caps = (spec.counter_capacity, spec.gauge_capacity,
            spec.status_capacity, spec.set_capacity, spec.histo_capacity)
    qs = sorted(union_qs) or [0.5]
    n_q = 4
    while n_q < len(qs):
        n_q <<= 1
    qcol = {v: i for i, v in enumerate(qs)}
    qs_padded = qs + [0.5] * (n_q - len(qs))
    buckets, idx_arrays = [], []
    for slots, cap in zip(need, caps):
        b = min(pad_bucket(len(slots), cap), FLUSH_BLOCK_ROWS)
        if len(slots) > b:
            raise ValueError("query gather exceeds one flush block")
        arr = np.zeros(b, np.int32)
        arr[:len(slots)] = slots
        buckets.append(b)
        idx_arrays.append(arr)
    return (pack_flush_inputs(qs_padded, idx_arrays), n_q,
            tuple(buckets), qcol)


def _flush_live_in_packed_core(state, flat, *, spec, n_q: int,
                               buckets: tuple, want_raw: bool = False):
    qs = jax.lax.bitcast_convert_type(flat[:n_q], jnp.float32)
    idx, off = [], n_q
    for n in buckets:
        idx.append(flat[off:off + n])
        off += n
    out = flush_live_core(state, qs, *idx, spec=spec, want_raw=want_raw)
    return _pack_outputs(out)


flush_live_in_packed = partial(
    jax.jit, static_argnames=("spec", "n_q", "buckets", "want_raw"))(
        _flush_live_in_packed_core)


def _flush_live_hist_packed_core(state, flat, hist, hflat, *, spec,
                                 hspec, n_q: int, buckets: tuple,
                                 want_raw: bool = False,
                                 clear: bool = False):
    """The flush program WITH the history tier's fused window write:
    identical flush math and packed output wire as
    _flush_live_in_packed_core, plus one extra scatter of the interval's
    values into ring column `col` — no second launch, no extra host
    traffic (ISSUE 18 tentpole). `hflat` carries the per-kind ring-row
    destinations (same bucket sizes as the flush's live-index buckets,
    sentinel rows drop) followed by the column scalar; the ring is
    DONATED and returned alongside the packed outputs.

    The write itself is history/device.write_window_core — the same
    function the host-fed backends and the replay oracle jit standalone
    — so both paths store bit-identical window bytes."""
    from veneur_tpu.history.device import write_window_core
    qs = jax.lax.bitcast_convert_type(flat[:n_q], jnp.float32)
    idx, off = [], n_q
    for n in buckets:
        idx.append(flat[off:off + n])
        off += n
    out = flush_live_core(state, qs, *idx, spec=spec, want_raw=True)
    dests, hoff = [], 0
    for n in buckets:
        dests.append(hflat[hoff:hoff + n])
        hoff += n
    col = hflat[hoff]
    vals = {
        "counter_hi": out["counter_hi"], "counter_lo": out["counter_lo"],
        "gauge": out["gauge"], "status": out["status"],
        "hll": out["raw_hll"],
        "h_mean": out["raw_h_mean"], "h_weight": out["raw_h_weight"],
        "h_min": out["histo_min"], "h_max": out["histo_max"],
        "h_count_hi": out["histo_count_hi"],
        "h_count_lo": out["histo_count_lo"],
        "h_sum_hi": out["histo_sum_hi"], "h_sum_lo": out["histo_sum_lo"],
    }
    new_hist = write_window_core(hist, vals, tuple(dests), col,
                                 hspec=hspec, clear=clear)
    if not want_raw:
        out = {k: v for k, v in out.items() if not k.startswith("raw_")}
    return _pack_outputs(out), new_hist


flush_live_hist_packed = partial(
    jax.jit,
    static_argnames=("spec", "hspec", "n_q", "buckets", "want_raw",
                     "clear"),
    donate_argnames=("hist",))(_flush_live_hist_packed_core)


def unpack_flush(packed, shapes: dict) -> dict:
    """Host-side inverse of the device packing: slice the flat f32 array
    back into named arrays. `shapes` maps key -> (shape, dtype); keys are
    consumed in sorted order, matching the packer."""
    import numpy as np
    out = {}
    off = 0
    for k in sorted(shapes):
        shape, dtype = shapes[k]
        n = int(np.prod(shape))
        if np.dtype(dtype) == np.uint8:
            words = n // 4
            out[k] = np.frombuffer(
                packed[off:off + words].tobytes(), np.uint8).reshape(shape)
            off += words
        elif np.dtype(dtype) == np.int32:
            out[k] = np.frombuffer(
                packed[off:off + n].tobytes(), np.int32).reshape(shape)
            off += n
        else:
            out[k] = packed[off:off + n].reshape(shape)
            off += n
    return out


def flush_live_shapes(spec, n_c, n_g, n_st, n_set, n_h, n_q,
                      want_raw: bool = False) -> dict:
    """The packer's output layout for given live-bucket sizes."""
    f32 = "float32"
    shapes = {
        "counter_hi": ((n_c,), f32), "counter_lo": ((n_c,), f32),
        "gauge": ((n_g,), f32), "status": ((n_st,), f32),
        "set_estimate": ((n_set,), f32),
        "histo_quantiles": ((n_h, n_q), f32),
        "histo_min": ((n_h,), f32), "histo_max": ((n_h,), f32),
        "histo_count_hi": ((n_h,), f32), "histo_count_lo": ((n_h,), f32),
        "histo_sum_hi": ((n_h,), f32), "histo_sum_lo": ((n_h,), f32),
        "histo_recip_hi": ((n_h,), f32), "histo_recip_lo": ((n_h,), f32),
        "histo_median": ((n_h,), f32),
    }
    if want_raw:
        cells = spec.centroids + spec.temp_cells
        shapes["raw_hll"] = ((n_set, spec.hll_words), "int32")
        shapes["raw_h_mean"] = ((n_h, cells), f32)
        shapes["raw_h_weight"] = ((n_h, cells), f32)
    return shapes


# Which live-index bucket each flush output key rides (0=counter,
# 1=gauge, 2=status, 3=set, 4=histo) — the tiled flush uses this to trim
# each block's padded rows back to the kind's real length.
FLUSH_KEY_KIND = {
    "counter_hi": 0, "counter_lo": 0, "gauge": 1, "status": 2,
    "set_estimate": 3, "raw_hll": 3,
    "histo_quantiles": 4, "histo_min": 4, "histo_max": 4,
    "histo_count_hi": 4, "histo_count_lo": 4, "histo_sum_hi": 4,
    "histo_sum_lo": 4, "histo_recip_hi": 4, "histo_recip_lo": 4,
    "histo_median": 4, "raw_h_mean": 4, "raw_h_weight": 4,
}

# Row-block size for the tiled flush: a flush whose live buckets exceed
# this compiles ONE block-shaped executable and loops over blocks on the
# host instead of minting a multi-million-row program (config 6's
# cycle-0 flush compile blew a 600s budget on the tunneled chip —
# VERDICT r04 #2; the reference streams flushes in fixed chunks too,
# flusher.go:169-298).
FLUSH_BLOCK_ROWS = 1 << 17


def live_slots(table, kind: str):
    """UNPADDED int32 slot-index array for a kind, in get_meta order."""
    import numpy as np
    metas = table.get_meta(kind)
    idx = np.zeros(len(metas), np.int32)
    for i, (slot, _m) in enumerate(metas):
        idx[i] = slot
    return idx


def pack_bucket_chunks(slots, buckets, block_i: int, fill: int = 0):
    """Block `block_i`'s per-kind index chunk, padded to each
    kind's STATIC bucket size (the tiled flush's executable-shape
    contract: every block invocation has identical bucket shapes).
    `fill` is the pad value: 0 for gather indices (clipped, outputs
    trimmed), an out-of-range sentinel for the history tier's scatter
    destinations (mode="drop" discards pads)."""
    import numpy as np
    out = []
    for sarr, b in zip(slots, buckets):
        c = sarr[block_i * b:(block_i + 1) * b]
        buf = np.full(b, fill, np.int32)
        buf[:len(c)] = c
        out.append(buf)
    return out





def pad_bucket(n: int, cap: int) -> int:
    """Size bucket for live-slot index arrays: next power of two (min 64),
    clamped to capacity — bounds compiled variants to ~log2(capacity).
    The 64 floor keeps small kinds (self-telemetry counters/gauges grow a
    little between the first and second flush) inside ONE bucket, so a
    steady server re-uses a single compiled flush program instead of
    minting a variant per flush — which both avoids recompiles and keeps
    the resident-executable count at two (see ingest_step_packed)."""
    p = 64
    while p < n:
        p <<= 1
    return min(p, max(cap, 1))


def live_indices(table, kind: str, cap: int):
    """Padded int32 slot-index array for a kind, in get_meta order (the
    positional contract flush_live's outputs follow). Pad-of-live_slots:
    ONE copy of the slot-extraction loop."""
    import numpy as np
    raw = live_slots(table, kind)
    idx = np.zeros(pad_bucket(len(raw), cap), np.int32)
    idx[:len(raw)] = raw
    return idx


def combine_flush_scalars(result: dict) -> dict:
    """Host-side finish of flush_core's output: collapse each two-float
    pair in FLOAT64 (exact for the pair's ~48 significand bits — the
    reference's int64 counters and float64 histo scalars,
    samplers/samplers.go:131,477-481, stay exact through here) and derive
    count/sum/avg/hmean. Works on any leading batch shape; the input dict
    is left untouched."""
    import numpy as np

    def f64(key):
        return (np.asarray(result[key + "_hi"], np.float64)
                + np.asarray(result[key + "_lo"], np.float64))

    out = {k: v for k, v in result.items()
           if not (k.endswith("_hi") or k.endswith("_lo"))}
    out["counter"] = f64("counter")
    count = f64("histo_count")
    total = f64("histo_sum")
    recip = f64("histo_recip")
    out["histo_count"] = count
    out["histo_sum"] = total
    out["histo_avg"] = total / np.maximum(count, 1e-30)
    out["histo_hmean"] = count / np.maximum(recip, 1e-30)
    return out


def finish_flush(out) -> dict:
    """Device flush output -> host numpy dict with pairs combined; the
    one boundary every flush consumer (server aggregators, tests, the
    multichip dryrun) goes through."""
    import numpy as np
    return combine_flush_scalars({k: np.asarray(v) for k, v in out.items()})
