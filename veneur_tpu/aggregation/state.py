"""Device-side aggregation state: the metric-key table.

The reference keeps 13 scope-split Go maps of sampler objects per worker
(reference worker.go:60-84) whose values are heap objects (int64 counters,
float64 gauges, HLL sketches, t-digests). Here the equivalent state is a
fixed-capacity struct-of-arrays, one slot per live MetricKey, assigned by the
host key dictionary (host.py). Strings never reach the device; scope and
name/tag metadata stay host-side.

Numeric representation notes:

- Counters (reference samplers/samplers.go:129: int64) are kept as a
  two-float f32 accumulator (utils/numerics.py) plus a plain f32 scatter
  target ``counter_acc`` that absorbs the per-batch scatter-adds; the host
  folds acc into (hi, lo) inside every ingest step, bounding
  rounding error to ~1e-6 relative while keeping the hot path a single
  scatter-add.
- Histogram digests are stored as (weight*mean, weight) rather than
  (mean, weight) so the ingest step is two scatter-adds with no dense
  mean recomputation; means materialize only during compaction/flush.
- Gauges are last-write-wins (reference samplers.go:225); batches are
  in arrival order, so per-batch "last sample per slot" + scatter-set
  preserves the semantics.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from veneur_tpu.ops import tdigest as td
from veneur_tpu.ops import hll


@dataclasses.dataclass(frozen=True)
class TableSpec:
    """Static capacities and sketch parameters of one key table (one shard's
    worth when sharded; see parallel/)."""
    counter_capacity: int = 1 << 16
    gauge_capacity: int = 1 << 14
    status_capacity: int = 1 << 10
    set_capacity: int = 1 << 10
    histo_capacity: int = 1 << 14
    compression: float = td.DEFAULT_COMPRESSION
    cells_per_k: int = td.DEFAULT_CELLS_PER_K
    exact_extremes: int = td.DEFAULT_EXACT_EXTREMES
    # 192 raw cells + 280 centroids = 472 columns — inside the 512 the
    # Pallas quantile kernel pads to anyway; temp feeds the per-batch
    # extremeness-priority allocation in step._histo_update
    temp_cells: int = 192
    hll_precision: int = hll.DEFAULT_PRECISION

    @property
    def centroids(self) -> int:
        return td.centroid_capacity(self.compression, self.cells_per_k,
                                    self.exact_extremes)

    @property
    def interior_cells(self) -> int:
        """k-cell columns between the 2·exact_extremes protected slots
        (see ops/tdigest.py DEFAULT_EXACT_EXTREMES)."""
        return self.centroids - 2 * self.exact_extremes

    @property
    def total_cells(self) -> int:
        """Centroid columns per digest row: C canonical k-cells plus T raw
        temp cells (the fixed-shape analogue of the reference digest's temp
        buffer, merging_digest.go:105-111). A key's first T samples land
        verbatim in temp cells — exact until compaction — so cold keys never
        suffer estimate-based cell assignment while their digest is still
        unformed."""
        return self.centroids + self.temp_cells

    @property
    def registers(self) -> int:
        return hll.num_registers(self.hll_precision)

    @property
    def hll_words(self) -> int:
        """int32 words per set row in the resident 6-bit packed HLL layout
        (ops/hll.py §packed); 3/8 of the register count — 12288 B/key vs
        16384 B dense u8 (and vs 65536 B for the i32-materialized registers
        an XLA scatter chain works over) at p=14."""
        return hll.packed_words(self.hll_precision)


class DeviceState(NamedTuple):
    """One flush interval's aggregation state. All arrays are per-slot;
    slot indices beyond a type's live count are simply zero/empty."""
    # counters
    counter_acc: jax.Array   # f32[Kc] unfolded scatter target
    counter_hi: jax.Array    # f32[Kc] two-float accumulator
    counter_lo: jax.Array
    # gauges / status checks (value part; message is host-side).  The stamp
    # arrays mark slots written this interval so the cross-replica merge has
    # a well-defined last-write winner (the reference's Gauge.Merge simply
    # overwrites in import order, samplers/samplers.go:297; our canonical
    # order is "highest replica index that wrote wins").
    gauge: jax.Array         # f32[Kg]
    gauge_stamp: jax.Array   # u8[Kg] 1 if written this interval
    status: jax.Array        # f32[Kst]
    status_stamp: jax.Array  # u8[Kst]
    # sets: 6-bit packed registers, register r at bit 6r little-endian
    # (ops/hll.py pack_registers; dense u8 exists only transiently in the
    # XLA fallback insert and at host boundaries)
    hll: jax.Array           # i32[Ks, W] where W = ceil(R*6/32)
    # histograms / timers: digest as (wm, w) + exact scalar aggregates.
    # Columns [0, C) are canonical k-cells; columns [C, C+T) are raw temp
    # cells holding individual samples since the last compaction.
    h_wm: jax.Array          # f32[Kh, C+T]  sum of weight*mean per cell
    h_w: jax.Array           # f32[Kh, C+T]
    h_temp_n: jax.Array      # i32[Kh] samples absorbed since last compact
    h_min: jax.Array         # f32[Kh]
    h_max: jax.Array         # f32[Kh]
    h_count_acc: jax.Array   # f32[Kh] + two-float, like counters
    h_count_hi: jax.Array
    h_count_lo: jax.Array
    h_sum_acc: jax.Array
    h_sum_hi: jax.Array
    h_sum_lo: jax.Array
    h_recip_acc: jax.Array   # sum of weight/value — harmonic mean support
    h_recip_hi: jax.Array    # (reference samplers/samplers.go:481,493)
    h_recip_lo: jax.Array


def empty_state_compiled(spec: TableSpec) -> DeviceState:
    """ONE compiled program materializing the whole empty state. The
    eager version dispatches ~20 distinct fill executables (one per
    array shape) — on the tunneled dev backend, where a process
    degrades to slow per-dispatch mode past a couple of resident
    executables (step.py ingest_step_packed), the per-interval swap
    must not be the thing that pushes it over."""
    return _empty_state_jit(spec=spec)


def empty_state(spec: TableSpec) -> DeviceState:
    f = jnp.float32
    kc, kg, kst = spec.counter_capacity, spec.gauge_capacity, spec.status_capacity
    ks, kh, c = spec.set_capacity, spec.histo_capacity, spec.total_cells
    z = jnp.zeros
    return DeviceState(
        counter_acc=z((kc,), f), counter_hi=z((kc,), f), counter_lo=z((kc,), f),
        gauge=z((kg,), f), gauge_stamp=z((kg,), jnp.uint8),
        status=z((kst,), f), status_stamp=z((kst,), jnp.uint8),
        hll=jnp.zeros((ks, spec.hll_words), jnp.int32),
        h_wm=z((kh, c), f), h_w=z((kh, c), f),
        h_temp_n=z((kh,), jnp.int32),
        h_min=jnp.full((kh,), jnp.inf, f),
        h_max=jnp.full((kh,), -jnp.inf, f),
        h_count_acc=z((kh,), f), h_count_hi=z((kh,), f), h_count_lo=z((kh,), f),
        h_sum_acc=z((kh,), f), h_sum_hi=z((kh,), f), h_sum_lo=z((kh,), f),
        h_recip_acc=z((kh,), f), h_recip_hi=z((kh,), f), h_recip_lo=z((kh,), f),
    )


_empty_state_jit = jax.jit(empty_state, static_argnames=("spec",))
