"""Host-side key dictionary and batch staging.

The reference resolves a MetricKey to a sampler object by Go map lookup inside
each worker (reference worker.go:108 Upsert). Here the host resolves
(name, type, joined_tags) to a dense slot index into the device arrays; the
device never sees strings. Slot metadata (name, tags, scope) stays host-side
for flush labeling, mirroring how the reference's MetricKey fields ride along
to InterMetric generation (reference samplers/samplers.go:147-158).

Slots are assigned shard-aware: slot = shard * per_shard + local index, where
shard = digest % n_shards and digest is the reference-compatible FNV-1a 32
(reference server.go:973,984 routes by Digest % numWorkers the same way).
This keeps every key's state resident on a single device when the table is
sharded over a mesh (parallel/), so ingest scatters never cross devices.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np

from veneur_tpu.aggregation.state import TableSpec
from veneur_tpu.aggregation.step import Batch
from veneur_tpu.utils.hashing import hll_reg_rho

# metric type classes that own a table
KINDS = ("counter", "gauge", "status", "set", "histogram", "timer")

# scopes, mirroring reference samplers/parser.go:66-70 MetricScope
SCOPE_MIXED = 0
SCOPE_LOCAL = 1
SCOPE_GLOBAL = 2


@dataclasses.dataclass
class SlotMeta:
    name: str
    tags: tuple
    scope: int
    kind: str
    hostname: str = ""
    message: str = ""  # status checks only
    # True while a histo slot has only ever been fed by the import path;
    # drives the global tier's aggregate suppression for mixed-scope
    # histograms (reference flusher.go:61-77 "avoid double counting":
    # imported mixed histos have no local scalars, so only percentiles
    # flush). Cleared on the first directly-sampled value.
    imported_only: bool = False
    # the parser's precomputed MetricKey.JoinedTags, when the allocation
    # site had it; lets flush labeling test for routing tags with ONE
    # substring scan instead of per-tag startswith (None -> join lazily)
    joined_tags: Optional[str] = None
    # flusher.generate_intermetrics cache: (tags list, sink route,
    # hostname) computed once per key per interval. The tags list is
    # SHARED by every InterMetric of the key — sinks must derive
    # (tags + [...]) rather than mutate, which they all do.
    _emit_prep: Optional[tuple] = dataclasses.field(
        default=None, repr=False, compare=False)


class _KindTable:
    __slots__ = ("capacity", "n_shards", "per_shard", "by_key", "meta",
                 "by_slot", "next_free", "dropped")

    def __init__(self, capacity: int, n_shards: int):
        self.capacity = capacity
        self.n_shards = n_shards
        self.per_shard = capacity // n_shards
        self.by_key: dict = {}
        self.meta: list = []          # parallel to allocation order
        self.by_slot: dict = {}       # slot -> SlotMeta, O(1) mutation
        self.next_free = [0] * n_shards
        self.dropped = 0

    def alloc(self, key, digest: int, name: str, tags: tuple, scope: int,
              kind: str, hostname: str = "", imported: bool = False,
              joined_tags=None) -> Optional[int]:
        """Allocate a slot for a new key (callers check by_key first —
        KeyTable.slot_for owns the hit path). Takes the SlotMeta FIELDS
        so the capacity check runs before any construction: during a
        cardinality explosion every re-arrival of a never-admitted key
        lands here, and paying a dataclass build per dropped sample is
        a regression at exactly the wrong time."""
        shard = digest % self.n_shards
        nxt = self.next_free[shard]
        if nxt >= self.per_shard:
            self.dropped += 1
            return None
        meta = SlotMeta(name=name, tags=tags, scope=scope, kind=kind,
                        hostname=hostname, imported_only=imported,
                        joined_tags=joined_tags)
        self.next_free[shard] = nxt + 1
        slot = shard * self.per_shard + nxt
        self.by_key[key] = slot
        self.meta.append((slot, meta))
        self.by_slot[slot] = meta
        return slot

    def reset(self):
        self.by_key.clear()
        self.meta.clear()
        self.by_slot.clear()
        self.next_free = [0] * self.n_shards


class KeyTable:
    """name/type/tags -> slot assignment for one flush interval.

    Timers and histograms share the histo device table (same sampler math,
    reference samplers.go:467) but are distinct key namespaces, as in the
    reference's separate timers/histograms maps (worker.go:66-67); we prefix
    the dict key with the kind.
    """

    # optional tables.pressure.TablePressure — attached by the backend's
    # swap() when table pressure management is enabled; stays None (one
    # predicted-not-taken branch on the MISS path only) otherwise
    pressure = None

    def __init__(self, spec: TableSpec, n_shards: int = 1):
        self.spec = spec
        self.n_shards = n_shards
        self.tables = {
            "counter": _KindTable(spec.counter_capacity, n_shards),
            "gauge": _KindTable(spec.gauge_capacity, n_shards),
            "status": _KindTable(spec.status_capacity, n_shards),
            "set": _KindTable(spec.set_capacity, n_shards),
            "histo": _KindTable(spec.histo_capacity, n_shards),
        }

    @staticmethod
    def _table_name(kind: str) -> str:
        return "histo" if kind in ("histogram", "timer") else kind

    def slot_for(self, kind: str, name: str, tags: tuple, scope: int,
                 digest: int, hostname: str = "",
                 imported: bool = False,
                 joined_tags: Optional[str] = None) -> Optional[int]:
        t = self.tables[self._table_name(kind)]
        # key identity is the JOINED tag string, exactly the reference's
        # MetricKey.JoinedTags (samplers/parser.go:76,412): an empty tag
        # section (`|#` -> [""]) joins to "" and shares the no-tags key,
        # and the C++ engine keys the same way (dogstatsd.cpp keybuf).
        # Callers on the hot path pass the parser's precomputed
        # UDPMetric.joined_tags to skip the per-sample join.
        if joined_tags is None:
            joined_tags = ",".join(tags)
        key = (kind, name, joined_tags)
        # steady-state hit path: ONE dict probe and nothing else —
        # constructing the SlotMeta (or even a closure to defer it) per
        # call cost ~25% of the whole staging hot loop
        slot = t.by_key.get(key)
        if slot is not None:
            return slot
        if self.pressure is not None:
            # miss path only — the pressure ladder (tables/pressure.py)
            # may redirect the key to a rollup/merge slot or admit it
            return self.pressure.admit(t, key, digest, name, tags, scope,
                                       kind, hostname, imported, joined_tags)
        return t.alloc(key, digest, name, tags, scope, kind,
                       hostname=hostname, imported=imported,
                       joined_tags=joined_tags)

    def get_meta(self, kind: str):
        """[(slot, SlotMeta)] in allocation order for flush labeling."""
        return self.tables[self._table_name(kind)].meta

    def meta_for_slot(self, kind: str, slot: int) -> Optional[SlotMeta]:
        return self.tables[self._table_name(kind)].by_slot.get(slot)

    def dropped(self) -> int:
        return sum(t.dropped for t in self.tables.values())

    def reset(self):
        for t in self.tables.values():
            t.reset()


@dataclasses.dataclass(frozen=True)
class BatchSpec:
    """Fixed staging sizes — one compiled ingest program per configuration."""
    counter: int = 8192
    gauge: int = 2048
    status: int = 256
    set: int = 4096
    histo: int = 8192
    histo_stat: int = 256  # imported-digest scalar lane (step.py)


class Batcher:
    """Stages parsed samples into numpy arrays and emits padded Batches.

    The reference's analogue is the PacketChan buffering between parser
    goroutines and workers (reference worker.go:31-55); here buffering is the
    staging arrays and "the worker" is the jitted ingest step.
    """

    def __init__(self, spec: TableSpec, bspec: BatchSpec = BatchSpec(),
                 on_batch: Optional[Callable[[Batch], None]] = None):
        self.spec = spec
        self.bspec = bspec
        self.on_batch = on_batch
        self._alloc()

    def _alloc(self):
        b = self.bspec
        self.c_slot = np.full(b.counter, self.spec.counter_capacity, np.int32)
        self.c_inc = np.zeros(b.counter, np.float32)
        self.g_slot = np.full(b.gauge, self.spec.gauge_capacity, np.int32)
        self.g_val = np.zeros(b.gauge, np.float32)
        self.st_slot = np.full(b.status, self.spec.status_capacity, np.int32)
        self.st_val = np.zeros(b.status, np.float32)
        self.s_slot = np.full(b.set, self.spec.set_capacity, np.int32)
        self.s_reg = np.zeros(b.set, np.int32)
        self.s_rho = np.zeros(b.set, np.uint8)
        self.h_slot = np.full(b.histo, self.spec.histo_capacity, np.int32)
        self.h_val = np.zeros(b.histo, np.float32)
        self.h_wt = np.zeros(b.histo, np.float32)
        self.hs_slot = np.full(b.histo_stat, self.spec.histo_capacity,
                               np.int32)
        self.hs_min = np.full(b.histo_stat, np.inf, np.float32)
        self.hs_max = np.full(b.histo_stat, -np.inf, np.float32)
        self.hs_recip = np.zeros(b.histo_stat, np.float32)
        self.nc = self.ng = self.nst = self.ns = self.nh = self.nhs = 0

    def _maybe_emit(self, n, cap):
        if n >= cap:
            self.emit()

    def add_counter(self, slot: int, value: float, rate: float):
        self.c_slot[self.nc] = slot
        self.c_inc[self.nc] = value * (1.0 / rate)
        self.nc += 1
        self._maybe_emit(self.nc, self.bspec.counter)

    def add_gauge(self, slot: int, value: float):
        self.g_slot[self.ng] = slot
        self.g_val[self.ng] = value
        self.ng += 1
        self._maybe_emit(self.ng, self.bspec.gauge)

    def add_status(self, slot: int, value: float):
        self.st_slot[self.nst] = slot
        self.st_val[self.nst] = value
        self.nst += 1
        self._maybe_emit(self.nst, self.bspec.status)

    def add_set(self, slot: int, member: bytes):
        reg, rho = hll_reg_rho(member, self.spec.hll_precision)
        self.s_slot[self.ns] = slot
        self.s_reg[self.ns] = reg
        self.s_rho[self.ns] = rho
        self.ns += 1
        self._maybe_emit(self.ns, self.bspec.set)

    def add_histo(self, slot: int, value: float, rate: float):
        self.h_slot[self.nh] = slot
        self.h_val[self.nh] = value
        self.h_wt[self.nh] = 1.0 / rate
        self.nh += 1
        self._maybe_emit(self.nh, self.bspec.histo)

    def add_histo_weighted(self, slot: int, value: float, weight: float):
        """Direct-weight variant for imported digest centroids (the
        global-tier re-add merge, reference samplers.go:726)."""
        self.h_slot[self.nh] = slot
        self.h_val[self.nh] = value
        self.h_wt[self.nh] = weight
        self.nh += 1
        self._maybe_emit(self.nh, self.bspec.histo)

    def add_histo_stats(self, slot: int, mn: float, mx: float,
                        recip: float):
        """Imported digest's exact min/max/reciprocalSum."""
        self.hs_slot[self.nhs] = slot
        self.hs_min[self.nhs] = mn
        self.hs_max[self.nhs] = mx
        self.hs_recip[self.nhs] = recip
        self.nhs += 1
        self._maybe_emit(self.nhs, self.bspec.histo_stat)

    # -- bulk staging (vectorized; the native engine's emit arrays are
    # split per shard and copied in slices, not per-sample Python calls) --
    def _bulk(self, dsts, srcs, n_attr: str, cap: int):
        n = len(srcs[0])
        i = 0
        while i < n:
            cur = getattr(self, n_attr)
            take = min(cap - cur, n - i)
            for dst, src in zip(dsts, srcs):
                dst[cur:cur + take] = src[i:i + take]
            setattr(self, n_attr, cur + take)
            i += take
            if getattr(self, n_attr) >= cap:
                self.emit()

    def add_counters_bulk(self, slots, incs):
        """incs already rate-weighted (the native stager applies 1/rate)."""
        self._bulk((self.c_slot, self.c_inc), (slots, incs), "nc",
                   self.bspec.counter)

    def add_gauges_bulk(self, slots, vals):
        self._bulk((self.g_slot, self.g_val), (slots, vals), "ng",
                   self.bspec.gauge)

    def add_sets_bulk(self, slots, regs, rhos):
        """(reg, rho) pre-hashed by the native engine."""
        self._bulk((self.s_slot, self.s_reg, self.s_rho),
                   (slots, regs, rhos), "ns", self.bspec.set)

    def add_histos_bulk(self, slots, vals, wts):
        self._bulk((self.h_slot, self.h_val, self.h_wt),
                   (slots, vals, wts), "nh", self.bspec.histo)

    def add_histo_stats_bulk(self, slots, mns, mxs, recips):
        """Imported-digest exact scalar stats, staged in slices (the
        native import decoder drains these per request)."""
        self._bulk((self.hs_slot, self.hs_min, self.hs_max,
                    self.hs_recip), (slots, mns, mxs, recips), "nhs",
                   self.bspec.histo_stat)

    def pending(self) -> int:
        return (self.nc + self.ng + self.nst + self.ns + self.nh
                + self.nhs)

    def force_emit(self) -> Batch:
        """Emit unconditionally (possibly all-padding) WITHOUT notifying
        on_batch — for callers that stack per-shard batches themselves
        (server/sharded_aggregator.py)."""
        b = self.emit(notify=False)
        if b is None:
            b = Batch(
                counter_slot=self.c_slot.copy(), counter_inc=self.c_inc.copy(),
                gauge_slot=self.g_slot.copy(), gauge_val=self.g_val.copy(),
                status_slot=self.st_slot.copy(), status_val=self.st_val.copy(),
                set_slot=self.s_slot.copy(), set_reg=self.s_reg.copy(),
                set_rho=self.s_rho.copy(),
                histo_slot=self.h_slot.copy(), histo_val=self.h_val.copy(),
                histo_wt=self.h_wt.copy(),
                histo_stat_slot=self.hs_slot.copy(),
                histo_stat_min=self.hs_min.copy(),
                histo_stat_max=self.hs_max.copy(),
                histo_stat_recip=self.hs_recip.copy(),
            )
        return b

    def emit(self, notify: bool = True) -> Optional[Batch]:
        """Build a padded Batch from staged samples, reset staging, and pass
        it to on_batch (if set and notify). Returns the Batch (None if
        empty)."""
        if self.pending() == 0:
            return None
        batch = Batch(
            counter_slot=self.c_slot.copy(), counter_inc=self.c_inc.copy(),
            gauge_slot=self.g_slot.copy(), gauge_val=self.g_val.copy(),
            status_slot=self.st_slot.copy(), status_val=self.st_val.copy(),
            set_slot=self.s_slot.copy(), set_reg=self.s_reg.copy(),
            set_rho=self.s_rho.copy(),
            histo_slot=self.h_slot.copy(), histo_val=self.h_val.copy(),
            histo_wt=self.h_wt.copy(),
            histo_stat_slot=self.hs_slot.copy(),
            histo_stat_min=self.hs_min.copy(),
            histo_stat_max=self.hs_max.copy(),
            histo_stat_recip=self.hs_recip.copy(),
        )
        # reset padding sentinels for the next batch
        self.c_slot[:self.nc] = self.spec.counter_capacity
        self.g_slot[:self.ng] = self.spec.gauge_capacity
        self.st_slot[:self.nst] = self.spec.status_capacity
        self.s_slot[:self.ns] = self.spec.set_capacity
        self.h_slot[:self.nh] = self.spec.histo_capacity
        self.hs_slot[:self.nhs] = self.spec.histo_capacity
        self.hs_min[:self.nhs] = np.inf
        self.hs_max[:self.nhs] = -np.inf
        self.hs_recip[:self.nhs] = 0.0
        self.c_inc[:self.nc] = 0.0
        self.h_wt[:self.nh] = 0.0
        self.nc = self.ng = self.nst = self.ns = self.nh = self.nhs = 0
        if notify and self.on_batch is not None:
            self.on_batch(batch)
        return batch
