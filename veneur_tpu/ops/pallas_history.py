"""Pallas TPU kernel for the history tier's masked HLL window merge.

A range query merges, per requested step, every selected ring column of
each matched set key: unpack the 6-bit packed registers, take the
register max over the selected columns, repack. The XLA fallback
(history/merge.py _merge_windows_xla) stages a dense u8 register block
per column through HBM on every fori step; rows are independent and a
(row-tile, col-tile) block of packed words fits in VMEM, so the fused
kernel keeps the whole unpack -> masked max -> repack loop on-chip and
revisits each output tile once per column tile (the matmul-style
accumulate-over-last-grid-axis pattern).

Same production gating as ops/pallas_digest.py (PR 8): a one-time
subprocess probe on a real TPU backend decides, VENEUR_TPU_PALLAS=1/0
forces, CPU always takes the XLA path. Parity with the XLA fallback is
asserted bit-exactly (packed words are integers) in
tests/test_history.py using interpret mode, which runs this same kernel
on CPU. A lowering or VMEM failure on real silicon fails the probe and
degrades range queries to the XLA chain rather than breaking them.
"""

from __future__ import annotations

import functools
import logging
import os
import sys

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from veneur_tpu.ops import hll

log = logging.getLogger("veneur_tpu.ops.pallas_history")

# [ROW_TILE, n_steps, R] i32 dense accumulator + one column's dense
# registers bound the VMEM working set; production p=14 (R=16384) with
# 8 rows x 16 steps is ~8MB — the probe, not arithmetic here, is the
# authority on whether a given shape fits.
ROW_TILE = 8
COL_TILE = 16


def _merge_kernel(sel_ref, rows_ref, out_ref, *, n_steps: int,
                  precision: int):
    rows = rows_ref[...]        # [T, wt, nw] packed words
    sel = sel_ref[...]          # [S, wt] 1.0 = column selected
    wt = rows.shape[1]
    r = hll.num_registers(precision)

    def body(i, acc):
        words = jax.lax.dynamic_index_in_dim(rows, i, axis=1,
                                             keepdims=False)
        regs = hll.unpack_registers(
            words, precision=precision).astype(jnp.int32)
        m = jax.lax.dynamic_index_in_dim(sel, i, axis=1, keepdims=False)
        cand = jnp.maximum(acc, regs[:, None, :])
        return jnp.where((m > 0.0)[None, :, None], cand, acc)

    acc = jax.lax.fori_loop(
        0, wt, body,
        jnp.zeros((rows.shape[0], n_steps, r), jnp.int32))

    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[...] = hll.pack_registers(
            acc.astype(jnp.uint8), precision=precision)

    @pl.when(pl.program_id(1) != 0)
    def _accumulate():
        cur = hll.unpack_registers(
            out_ref[...], precision=precision).astype(jnp.int32)
        out_ref[...] = hll.pack_registers(
            jnp.maximum(cur, acc).astype(jnp.uint8), precision=precision)


def merge_windows_packed(rows, sel, *, precision: int,
                         interpret: bool = False):
    """rows i32[N, W, nw] packed HLL windows, sel f32[S, W] selection
    masks -> i32[N, S, nw]: per step, the packed register max over the
    selected columns. Pads rows/cols with zeros (the register-max
    identity), so padding never changes an estimate."""
    n, w, nw = rows.shape
    s = int(sel.shape[0])
    n_pad = -(-n // ROW_TILE) * ROW_TILE
    w_pad = -(-w // COL_TILE) * COL_TILE
    if n_pad != n or w_pad != w:
        rows = jnp.pad(rows, ((0, n_pad - n), (0, w_pad - w), (0, 0)))
        sel = jnp.pad(sel, ((0, 0), (0, w_pad - w)))
    grid = (n_pad // ROW_TILE, w_pad // COL_TILE)
    out = pl.pallas_call(
        functools.partial(_merge_kernel, n_steps=s, precision=precision),
        grid=grid,
        in_specs=[
            pl.BlockSpec((s, COL_TILE), lambda i, j: (0, j)),
            pl.BlockSpec((ROW_TILE, COL_TILE, nw), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((ROW_TILE, s, nw), lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, s, nw), jnp.int32),
        interpret=interpret,
    )(sel, rows)
    return out[:n]


_PROBE_RESULT = None


def enabled() -> bool:
    """Use the Pallas merge? VENEUR_TPU_PALLAS=1/0 forces (the same
    switch as the digest kernel — operators pin the whole Pallas
    surface at once); default is a one-time probe compile on a real-TPU
    backend, never on CPU."""
    global _PROBE_RESULT
    force = os.environ.get("VENEUR_TPU_PALLAS", "")
    if force == "1":
        return True
    if force == "0":
        return False
    if _PROBE_RESULT is None:
        try:
            if jax.devices()[0].platform == "cpu":
                _PROBE_RESULT = False
            else:
                _PROBE_RESULT = _run_probe_bounded()
        except Exception as e:  # noqa: BLE001 — any failure => XLA path
            log.warning("pallas history merge unavailable, using XLA "
                        "path: %s", e)
            _PROBE_RESULT = False
    return _PROBE_RESULT


def _probe() -> bool:
    """Probe under jit, the production calling context (the range-merge
    program wraps this call), with a value check strict enough to
    reject a miscompiled lowering."""
    p = 10
    regs = jnp.zeros((1, 2, hll.num_registers(p)), jnp.uint8)
    regs = regs.at[0, 0, 3].set(7).at[0, 1, 3].set(5).at[0, 1, 9].set(2)
    rows = hll.pack_registers(regs, precision=p)
    sel = jnp.asarray([[1.0, 1.0]], jnp.float32)
    out = jax.jit(functools.partial(
        merge_windows_packed, precision=p))(rows, sel)
    want = hll.pack_registers(jnp.maximum(regs[:, 0], regs[:, 1]),
                              precision=p)
    return bool(jnp.array_equal(out[:, 0, :], want))


def _run_probe_bounded(budget_s: float = 60.0) -> bool:
    """Run the probe in a subprocess with a hard budget — same
    rationale as pallas_digest._run_probe_bounded: a wedged compile
    service must not stall the first range query, and a timed-out
    in-process thread abandoned inside the JAX runtime aborts the
    interpreter at teardown."""
    import subprocess
    code = ("import sys; sys.path.insert(0, %r); "
            "from veneur_tpu.ops.pallas_history import _probe; "
            "print('PALLAS_OK' if _probe() else 'PALLAS_NO')"
            % os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=budget_s)
    except subprocess.TimeoutExpired:
        log.warning("pallas history probe exceeded %.0fs; using XLA "
                    "path", budget_s)
        return False
    ok = "PALLAS_OK" in proc.stdout
    if not ok:
        log.warning("pallas history merge unavailable, using XLA path "
                    "(probe rc=%d)", proc.returncode)
    return ok
