"""Count-min sketch: heavy-hitter counting for unbounded tag cardinality.

No reference counterpart — this is the new sketch kernel BASELINE config 5
calls for (10M-tag SSF span firehose → top-K tag frequencies). Same
TPU-native shape as the other sketches (SURVEY §2.9): strings hash on the
host, the device holds a fixed [depth, width] counter table updated by one
batched scatter-add per ingest step, and estimates are a min-reduce over
depth gathered rows.

Guarantee (Cormode & Muthukrishnan): estimate >= true count, and
estimate <= true + eps*N with probability 1-delta for width >= e/eps,
depth >= ln(1/delta).
"""

from __future__ import annotations


from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veneur_tpu.utils.hashing import fnv1a_64, splitmix64

DEFAULT_DEPTH = 4
DEFAULT_WIDTH = 1 << 16


def _check_width(width: int):
    if width & (width - 1) or width <= 0:
        raise ValueError(f"count-min width must be a power of two, "
                         f"got {width} (column hashing masks low bits)")


def empty_counters(depth: int = DEFAULT_DEPTH, width: int = DEFAULT_WIDTH):
    _check_width(width)
    return jnp.zeros((depth, width), jnp.float32)


def columns_for(member: bytes, depth: int = DEFAULT_DEPTH,
                width: int = DEFAULT_WIDTH) -> np.ndarray:
    """Host-side: the D column indices for one item. One 64-bit base hash,
    re-mixed per row — independent-enough row hashes without rehashing the
    bytes D times."""
    h = fnv1a_64(member)
    return np.asarray(
        [splitmix64(h ^ (0x9E3779B97F4A7C15 * (d + 1))) & (width - 1)
         for d in range(depth)], np.int64).astype(np.int32)


def columns_for_batch(members: List[bytes], depth: int = DEFAULT_DEPTH,
                      width: int = DEFAULT_WIDTH) -> np.ndarray:
    return np.stack([columns_for(m, depth, width) for m in members])


@jax.jit
def insert_batch(counters, cols, weights):
    """counters f32[D, W], cols i32[B, D] (negative = padding, dropped),
    weights f32[B]. One flattened scatter-add for all D rows."""
    d, w = counters.shape
    b = cols.shape[0]
    rows = jnp.arange(d, dtype=jnp.int32)[None, :]        # [1, D]
    flat = jnp.where(cols >= 0, rows * w + cols, d * w)   # [B, D]
    upd = jnp.broadcast_to(weights[:, None], (b, d))
    out = counters.reshape(-1).at[flat.reshape(-1)].add(
        upd.reshape(-1), mode="drop")
    return out.reshape(d, w)


@jax.jit
def estimate(counters, cols):
    """Point estimates: min over depth of the gathered cells.
    counters f32[D, W], cols i32[B, D] -> f32[B]."""
    d = counters.shape[0]
    rows = jnp.arange(d, dtype=jnp.int32)[None, :]
    vals = counters[rows, jnp.maximum(cols, 0)]           # [B, D]
    return jnp.where((cols >= 0).all(axis=1), vals.min(axis=1), 0.0)


@jax.jit
def merge(a, b):
    """Sketch union: counter-wise sum (mergeable like the other sketches —
    the global tier adds tables)."""
    return a + b


class HeavyHitters:
    """Host-side top-K tracking over a device sketch.

    Each batch: insert on device, estimate the batch's own items on device,
    then keep a bounded dict of the highest-estimate members (pruned to
    2K when it exceeds 4K). The sketch's one-sided error makes this a
    superset-biased top-K, which is the standard CMS heavy-hitter
    construction."""

    def __init__(self, k: int = 100, depth: int = DEFAULT_DEPTH,
                 width: int = DEFAULT_WIDTH):
        self.k = k
        self.depth = depth
        self.width = width
        self.counters = empty_counters(depth, width)
        self.candidates: Dict[bytes, float] = {}
        self.total = 0.0

    def update(self, members: List[bytes],
               weights: np.ndarray = None) -> None:
        if not members:
            return
        cols = columns_for_batch(members, self.depth, self.width)
        w = (np.ones(len(members), np.float32) if weights is None
             else np.asarray(weights, np.float32))
        self.counters = insert_batch(self.counters, jnp.asarray(cols),
                                     jnp.asarray(w))
        self.total += float(w.sum())
        est = np.asarray(estimate(self.counters, jnp.asarray(cols)))
        for m, e in zip(members, est):
            self.candidates[m] = float(e)
        if len(self.candidates) > 4 * self.k:
            self._prune()

    def _prune(self):
        keep = sorted(self.candidates.items(), key=lambda kv: -kv[1])
        self.candidates = dict(keep[:2 * self.k])

    def top(self, k: int = None) -> List[Tuple[bytes, float]]:
        k = k or self.k
        return sorted(self.candidates.items(), key=lambda kv: -kv[1])[:k]

    def reset(self):
        self.counters = empty_counters(self.depth, self.width)
        self.candidates.clear()
        self.total = 0.0
