"""Count-min sketch: heavy-hitter counting for unbounded tag cardinality.

No reference counterpart — this is the new sketch kernel BASELINE config 5
calls for (10M-tag SSF span firehose → top-K tag frequencies). Same
TPU-native shape as the other sketches (SURVEY §2.9): strings hash on the
host, the device holds a fixed [depth, width] counter table updated by one
batched scatter-add per ingest step, and estimates are a min-reduce over
depth gathered rows.

Guarantee (Cormode & Muthukrishnan): estimate >= true count, and
estimate <= true + eps*N with probability 1-delta for width >= e/eps,
depth >= ln(1/delta).
"""

from __future__ import annotations


from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from veneur_tpu.utils.hashing import fnv1a_64, splitmix64

DEFAULT_DEPTH = 4
DEFAULT_WIDTH = 1 << 16


def _check_width(width: int):
    if width & (width - 1) or width <= 0:
        raise ValueError(f"count-min width must be a power of two, "
                         f"got {width} (column hashing masks low bits)")


def empty_counters(depth: int = DEFAULT_DEPTH, width: int = DEFAULT_WIDTH):
    _check_width(width)
    return jnp.zeros((depth, width), jnp.float32)


_M64 = (1 << 64) - 1


def _splitmix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorized utils.hashing.splitmix64 (numpy uint64 wraps mod 2^64,
    matching the scalar's `& _M64`)."""
    x = x + np.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def columns_for(member: bytes, depth: int = DEFAULT_DEPTH,
                width: int = DEFAULT_WIDTH) -> np.ndarray:
    """Host-side: the D column indices for one item. One 64-bit base hash,
    re-mixed per row — independent-enough row hashes without rehashing the
    bytes D times."""
    h = fnv1a_64(member)
    return np.asarray(
        [splitmix64(h ^ (0x9E3779B97F4A7C15 * (d + 1))) & (width - 1)
         for d in range(depth)], np.int64).astype(np.int32)


def columns_for_batch(members: List[bytes], depth: int = DEFAULT_DEPTH,
                      width: int = DEFAULT_WIDTH) -> np.ndarray:
    """Batch columns_for: one C call for the member hashes, numpy for the
    per-row remix (bit-identical to the scalar; asserted in tests). The
    per-member Python loop was the span firehose's top host cost."""
    from veneur_tpu import native
    if native.available():
        hs = native.hash64_batch(members)
    else:
        hs = np.asarray([fnv1a_64(m) for m in members], np.uint64)
    cols = np.empty((len(members), depth), np.int32)
    mask = np.uint64(width - 1)
    with np.errstate(over="ignore"):
        for d in range(depth):
            salt = np.uint64((0x9E3779B97F4A7C15 * (d + 1)) & _M64)
            cols[:, d] = (_splitmix64_np(hs ^ salt) & mask).astype(np.int32)
    return cols


@jax.jit
def insert_batch(counters, cols, weights):
    """counters f32[D, W], cols i32[B, D] (negative = padding, dropped),
    weights f32[B]. One flattened scatter-add for all D rows."""
    d, w = counters.shape
    b = cols.shape[0]
    rows = jnp.arange(d, dtype=jnp.int32)[None, :]        # [1, D]
    flat = jnp.where(cols >= 0, rows * w + cols, d * w)   # [B, D]
    upd = jnp.broadcast_to(weights[:, None], (b, d))
    out = counters.reshape(-1).at[flat.reshape(-1)].add(
        upd.reshape(-1), mode="drop")
    return out.reshape(d, w)


@jax.jit
def estimate(counters, cols):
    """Point estimates: min over depth of the gathered cells.
    counters f32[D, W], cols i32[B, D] -> f32[B]."""
    d = counters.shape[0]
    rows = jnp.arange(d, dtype=jnp.int32)[None, :]
    vals = counters[rows, jnp.maximum(cols, 0)]           # [B, D]
    return jnp.where((cols >= 0).all(axis=1), vals.min(axis=1), 0.0)


@jax.jit
def insert_and_estimate(counters, cols, weights):
    """insert_batch + estimate of the same items in ONE compiled program
    (one dispatch per batch instead of two — dispatch count is the scarce
    resource on a tunneled chip, and the update path always wants both)."""
    d, w = counters.shape
    b = cols.shape[0]
    rows = jnp.arange(d, dtype=jnp.int32)[None, :]
    flat = jnp.where(cols >= 0, rows * w + cols, d * w)
    upd = jnp.broadcast_to(weights[:, None], (b, d))
    out = counters.reshape(-1).at[flat.reshape(-1)].add(
        upd.reshape(-1), mode="drop").reshape(d, w)
    vals = out[rows, jnp.maximum(cols, 0)]
    est = jnp.where((cols >= 0).all(axis=1), vals.min(axis=1), 0.0)
    return out, est


@jax.jit
def merge(a, b):
    """Sketch union: counter-wise sum (mergeable like the other sketches —
    the global tier adds tables)."""
    return a + b


class HeavyHitters:
    """Host-side top-K tracking over a device sketch.

    Each batch: insert on device, estimate the batch's own items on device,
    then keep a bounded dict of the highest-estimate members (pruned to
    2K when it exceeds 4K). The sketch's one-sided error makes this a
    superset-biased top-K, which is the standard CMS heavy-hitter
    construction."""

    def __init__(self, k: int = 100, depth: int = DEFAULT_DEPTH,
                 width: int = DEFAULT_WIDTH):
        self.k = k
        self.depth = depth
        self.width = width
        self.counters = empty_counters(depth, width)
        self.candidates: Dict[bytes, float] = {}
        self.total = 0.0

    def update(self, members: List[bytes],
               weights: np.ndarray = None) -> None:
        if not members:
            return
        cols = jnp.asarray(columns_for_batch(members, self.depth,
                                             self.width))
        w = (np.ones(len(members), np.float32) if weights is None
             else np.asarray(weights, np.float32))
        self.counters, est = insert_and_estimate(self.counters, cols,
                                                 jnp.asarray(w))
        self.total += float(w.sum())
        est = np.asarray(est)
        for m, e in zip(members, est):
            self.candidates[m] = float(e)
        if len(self.candidates) > 4 * self.k:
            self._prune()

    def _prune(self):
        keep = sorted(self.candidates.items(), key=lambda kv: -kv[1])
        self.candidates = dict(keep[:2 * self.k])

    def top(self, k: int = None) -> List[Tuple[bytes, float]]:
        k = k or self.k
        return sorted(self.candidates.items(), key=lambda kv: -kv[1])[:k]

    def reset(self):
        self.counters = empty_counters(self.depth, self.width)
        self.candidates.clear()
        self.total = 0.0
