"""Batched fixed-shape t-digest for TPU.

The reference maintains one Dunning merging t-digest per timer/histogram key
(reference tdigest/merging_digest.go: data-dependent centroid counts, a temp
buffer, and a sequential greedy merge pass). That formulation is hostile to
XLA: variable length, data-dependent control flow, pointer-chasing merge.

This module re-derives the *same mathematical object* — centroids sized by the
arcsine scale function k1(q) = δ/(2π)·asin(2q−1) (reference
merging_digest.go:259-262 ``indexEstimate``) — as a fully parallel,
fixed-shape computation:

  1. each digest is a fixed array of C (mean, weight) slots; weight == 0 marks
     an empty slot,
  2. "merge" = sort the combined centroids of each row by mean, take the
     per-row cumulative weight, assign every centroid to the k-cell
     ``floor(cells_per_k · (k1(q_mid) − k1(0)))`` of its weight midpoint, and
     segment-reduce (weighted mean) each cell,
  3. all reductions use the sort → cumsum → unique-index scatter → running-max
     → diff pattern, which XLA tiles well on TPU (no serialized scatter-adds).

Bucketing by unit k-cells satisfies the same Δk ≤ 1 merge invariant the
reference enforces greedily; ``cells_per_k = 3`` (third-cells) plus
exact-extreme protection (below) make quantile accuracy strictly dominate the
reference's envelope (reference tdigest/histo_test.go:27 asserts median within
2% at δ=1000; BASELINE demands ≤1% p99 error at δ=100, which this module holds
PER KEY — the reference's greedy merge measures up to 9.6% on heavy-tailed
mid-size keys). Unlike the reference — whose ``Merge`` shuffles
centroid insertion order with rand.Perm to avoid bias
(merging_digest.go:374-389) — this merge is deterministic and order-free:
the same multiset of centroids always produces the same digest.

All functions operate on arrays with an arbitrary batch of leading dims and a
trailing centroid dim C, so one jitted program updates every key in a sharded
key table at once.
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from veneur_tpu.utils.numerics import twofloat_add, twofloat_merge

DEFAULT_COMPRESSION = 100.0
# 3 cells per k-unit: at δ=100 the thick-cell interpolation bias for
# very hot keys (p99 deep in the interior) shrinks quadratically with
# cell width; cpk=3 measured 0.60% worst-key p99 error at n=56k vs
# 1.03% at cpk=2 (the ≤1% budget is per key, BASELINE.md).
DEFAULT_CELLS_PER_K = 3
# Exact-extreme protection: the bottom/top E centroids (by mean) are
# NEVER merged during compression — they pass through as-is, so a value
# that entered as a raw sample stays a raw sample (weight intact) at the
# distribution's ends for as long as it ranks there. This is what closes
# the per-key p99 tail error for mid-size keys (n ≈ 300..6000), where
# plain k-cells hold 2-4 heavy-tailed samples each and interpolation
# across their merged means erred up to ~10% (VERDICT r04 weak #3). The
# reference's greedy merge has the same 2-sample tail cells (measured
# max 9.6% on the same data) — this is a strict accuracy improvement
# over the reference algorithm, not a port of it.
DEFAULT_EXACT_EXTREMES = 64


def interior_capacity(compression: float = DEFAULT_COMPRESSION,
                      cells_per_k: int = DEFAULT_CELLS_PER_K) -> int:
    """k-cell slots between the protected extremes: k1 spans δ/2 total
    k-units over q∈[0,1], so at most ceil(δ/2 · cells_per_k) + 1
    occupied cells."""
    return int(math.ceil(compression / 2.0 * cells_per_k)) + 2


def centroid_capacity(compression: float = DEFAULT_COMPRESSION,
                      cells_per_k: int = DEFAULT_CELLS_PER_K,
                      exact_extremes: int = DEFAULT_EXACT_EXTREMES) -> int:
    """Number of centroid slots per digest: 2·E protected extreme slots
    around the k-cell interior, rounded up to a multiple of 8 for TPU
    sublane friendliness."""
    c = interior_capacity(compression, cells_per_k) + 2 * exact_extremes
    return (c + 7) // 8 * 8


class TDigestTable(NamedTuple):
    """A batch of t-digests plus the exact scalar aggregates the reference
    keeps alongside each Histo (reference samplers/samplers.go:477-481:
    LocalWeight/Min/Max/Sum/ReciprocalSum).

    Leading dims = key axis (arbitrary shape); trailing dim of mean/weight = C.
    Sums use two-float compensated accumulation (see utils.numerics) in place
    of the reference's float64.
    """
    mean: jax.Array      # f32[..., C]
    weight: jax.Array    # f32[..., C]; 0 = empty slot
    min: jax.Array       # f32[...]
    max: jax.Array       # f32[...]
    count_hi: jax.Array  # f32[...]  total weight (scaled by 1/sample_rate)
    count_lo: jax.Array
    sum_hi: jax.Array    # f32[...]  Σ w·v
    sum_lo: jax.Array
    recip_hi: jax.Array  # f32[...]  Σ w/v (for harmonic mean)
    recip_lo: jax.Array


def empty_table(key_shape, compression: float = DEFAULT_COMPRESSION,
                cells_per_k: int = DEFAULT_CELLS_PER_K,
                exact_extremes: int = DEFAULT_EXACT_EXTREMES) -> TDigestTable:
    key_shape = tuple(key_shape) if not isinstance(key_shape, int) else (key_shape,)
    c = centroid_capacity(compression, cells_per_k, exact_extremes)
    f = jnp.float32
    return TDigestTable(
        mean=jnp.zeros(key_shape + (c,), f),
        weight=jnp.zeros(key_shape + (c,), f),
        min=jnp.full(key_shape, jnp.inf, f),
        max=jnp.full(key_shape, -jnp.inf, f),
        count_hi=jnp.zeros(key_shape, f),
        count_lo=jnp.zeros(key_shape, f),
        sum_hi=jnp.zeros(key_shape, f),
        sum_lo=jnp.zeros(key_shape, f),
        recip_hi=jnp.zeros(key_shape, f),
        recip_lo=jnp.zeros(key_shape, f),
    )


def _k1(q, compression):
    # arcsine scale function; same family as reference merging_digest.go:259.
    q = jnp.clip(q, 0.0, 1.0)
    return compression / (2.0 * jnp.pi) * jnp.arcsin(2.0 * q - 1.0)


def compress_rows(mean, weight, *, compression: float = DEFAULT_COMPRESSION,
                  cells_per_k: int = DEFAULT_CELLS_PER_K,
                  out_c: int | None = None,
                  exact_extremes: int = DEFAULT_EXACT_EXTREMES):
    """Compress each row of (mean, weight) centroids to ≤ out_c centroids:
    the bottom/top `exact_extremes` occupied centroids pass through
    UNMERGED (exact-extreme protection — see DEFAULT_EXACT_EXTREMES);
    everything between is k-cell bucketed and segment-reduced.

    mean, weight: f32[..., M] with weight == 0 marking empties. Rows need not
    be sorted. Returns (mean', weight') of shape [..., out_c]; occupied cells
    appear in ascending-mean order at their cell index, empties have weight 0.

    This is the whole merge: equivalent to the reference's mergeAllTemps
    (merging_digest.go:140-224) but parallel across rows and within a row —
    and strictly more accurate at the tails, where the reference merges
    adjacent extreme samples into 2-4-sample centroids.
    """
    if out_c is None:
        out_c = centroid_capacity(compression, cells_per_k, exact_extremes)
    interior = out_c - 2 * exact_extremes
    assert interior >= 8, (
        f"out_c={out_c} leaves no k-cell interior around "
        f"2x{exact_extremes} protected extremes")
    lead = mean.shape[:-1]
    m_in = mean.reshape((-1, mean.shape[-1]))
    w_in = weight.reshape((-1, weight.shape[-1]))
    n, m_len = m_in.shape

    occupied = w_in > 0
    sort_key = jnp.where(occupied, m_in, jnp.inf)
    order = jnp.argsort(sort_key, axis=1)
    m = jnp.take_along_axis(m_in, order, axis=1)
    w = jnp.where(jnp.take_along_axis(occupied, order, axis=1),
                  jnp.take_along_axis(w_in, order, axis=1), 0.0)

    tot = jnp.sum(w, axis=1, keepdims=True)
    cum = jnp.cumsum(w, axis=1)
    q_mid = (cum - 0.5 * w) / jnp.maximum(tot, jnp.float32(1e-30))
    k0 = -compression / 4.0  # k1(0)
    cell = jnp.floor((_k1(q_mid, compression) - k0)
                     * cells_per_k).astype(jnp.int32)
    cell = jnp.clip(cell, 0, interior - 1) + exact_extremes
    if exact_extremes > 0:
        # Protected extremes scatter to dedicated end columns: bottom
        # rank r → column r, top rank r' → column out_c-1-r'. Output
        # columns stay non-decreasing along the sorted row (bottom block
        # < interior block < top block), so the run-end machinery below
        # needs no change — and protected runs are single-element, which
        # is exactly what makes them exact.
        occ32 = (w > 0).astype(jnp.int32)
        rnk = jnp.cumsum(occ32, axis=1) - 1      # rank among occupied
        r_top = jnp.sum(occ32, axis=1, keepdims=True) - 1 - rnk
        cell = jnp.where(rnk < exact_extremes, rnk,
                         jnp.where(r_top < exact_extremes,
                                   out_c - 1 - r_top, cell))
    # empties → out-of-bounds cell so their scatter is dropped
    cell = jnp.where(w > 0, cell, out_c)

    # Per-(row, cell) sums via cumulative-scatter-diff: cells are sorted within
    # each row, so scatter each run's *trailing cumulative* at a unique index,
    # forward-fill empty cells with a running max, and difference.
    cum_wm = jnp.cumsum(w * m, axis=1)
    is_last = jnp.concatenate(
        [cell[:, :-1] != cell[:, 1:], jnp.ones((n, 1), bool)], axis=1)
    rows = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, m_len))
    flat = jnp.where(is_last, rows * out_c + jnp.minimum(cell, out_c - 1),
                     n * out_c)
    flat = jnp.where(cell < out_c, flat, n * out_c)

    # in-bounds indices are unique (one per run end) but the drop sentinel
    # is duplicated, so no unique_indices hint — mode="drop" discards
    # sentinels. ONE helper so the flat-index/sentinel scheme lives in
    # one place for all four scatters below.
    def scatter_at_run_ends(vals):
        return jnp.zeros((n * out_c,), w.dtype).at[flat.ravel()].set(
            vals.ravel(), mode="drop").reshape(n, out_c)

    end_w = jnp.zeros((n * out_c,), w.dtype).at[flat.ravel()].max(
        cum.ravel(), mode="drop").reshape(n, out_c)
    end_wm = scatter_at_run_ends(cum_wm)
    # forward-fill: empty cells carry the previous cumulative
    fill_w = jax.lax.cummax(end_w, axis=1)
    has = end_w > 0
    # cum_wm can legitimately be non-monotone only if means are negative; track
    # occupancy explicitly instead of relying on positivity.
    end_wm = jnp.where(has, end_wm, 0.0)
    idx = jax.lax.cummax(jnp.where(has, jnp.arange(out_c, dtype=jnp.int32)[None, :], 0), axis=1)
    fill_wm = jnp.take_along_axis(end_wm, idx, axis=1)
    w_out = fill_w - jnp.concatenate(
        [jnp.zeros((n, 1), w.dtype), fill_w[:, :-1]], axis=1)
    wm_out = fill_wm - jnp.concatenate(
        [jnp.zeros((n, 1), w.dtype), fill_wm[:, :-1]], axis=1)
    # SINGLE-entry runs bypass the cumulative diff entirely: differencing
    # two ~total-magnitude cumulatives costs f32 ulps of the TOTAL (at a
    # 2^20-weight row that's ~0.1 absolute on a weight-1 centroid), which
    # would erode exactly the protected extremes this compress exists to
    # keep raw. Their (m, w) scatter through VERBATIM — bit-exact, no
    # multiply/divide round-trip. (cell == out_c entries are already the
    # drop sentinel in `flat`, so no extra mask is needed.)
    is_first = jnp.concatenate(
        [jnp.ones((n, 1), bool), cell[:, 1:] != cell[:, :-1]], axis=1)
    single = is_first & is_last
    w_single = scatter_at_run_ends(jnp.where(single, w, 0.0))
    m_single = scatter_at_run_ends(jnp.where(single, m, 0.0))
    w_out = jnp.where(w_single > 0, w_single, w_out)
    m_out = jnp.where(
        w_single > 0, m_single,
        jnp.where(w_out > 0, wm_out / jnp.maximum(w_out, 1e-30), 0.0))
    return (m_out.reshape(lead + (out_c,)), w_out.reshape(lead + (out_c,)))


def merge_tables(a: TDigestTable, b: TDigestTable, *,
                 compression: float = DEFAULT_COMPRESSION,
                 cells_per_k: int = DEFAULT_CELLS_PER_K,
                 exact_extremes: int = DEFAULT_EXACT_EXTREMES) -> TDigestTable:
    """Key-wise merge of two digest tables (the global-aggregation merge;
    reference samplers/samplers.go:726 Histo.Merge → tdigest Merge).
    Exact-extreme protection composes through the merge: the union's
    bottom/top E centroids survive unmerged."""
    out_c = a.mean.shape[-1]
    m = jnp.concatenate([a.mean, b.mean], axis=-1)
    w = jnp.concatenate([a.weight, b.weight], axis=-1)
    m2, w2 = compress_rows(m, w, compression=compression,
                           cells_per_k=cells_per_k, out_c=out_c,
                           exact_extremes=exact_extremes)
    ch, cl = twofloat_merge(a.count_hi, a.count_lo, b.count_hi, b.count_lo)
    sh, sl = twofloat_merge(a.sum_hi, a.sum_lo, b.sum_hi, b.sum_lo)
    rh, rl = twofloat_merge(a.recip_hi, a.recip_lo, b.recip_hi, b.recip_lo)
    return TDigestTable(
        mean=m2, weight=w2,
        min=jnp.minimum(a.min, b.min), max=jnp.maximum(a.max, b.max),
        count_hi=ch, count_lo=cl, sum_hi=sh, sum_lo=sl,
        recip_hi=rh, recip_lo=rl)


def _quantiles_one(mean, weight, mn, mx, qs):
    """Quantiles of a single digest [C] at qs [Q] via midpoint interpolation
    (reference merging_digest.go:302 Quantile)."""
    order = jnp.argsort(jnp.where(weight > 0, mean, jnp.inf))
    m = mean[order]
    w = jnp.where(weight[order] > 0, weight[order], 0.0)
    tot = jnp.sum(w)
    cum = jnp.cumsum(w)
    mid = cum - 0.5 * w
    # append virtual endpoints (0 → min, tot → max); empties collapse onto max
    xs = jnp.where(w > 0, mid, tot)
    ys = jnp.where(w > 0, m, mx)
    xs = jnp.concatenate([jnp.zeros((1,), xs.dtype), xs, tot[None]])
    ys = jnp.concatenate([mn[None], ys, mx[None]])
    t = qs * tot
    out = jnp.interp(t, xs, ys)
    return jnp.where(tot > 0, out, jnp.float32(jnp.nan))


def quantiles(table: TDigestTable, qs) -> jax.Array:
    """Quantiles for every digest: returns f32[..., Q]. On a real TPU
    backend this routes to the fused Pallas kernel (sort + cumsum +
    interpolation in one VMEM pass, ops/pallas_digest.py) when its probe
    compile succeeds; the XLA vmap path is the portable fallback and the
    parity oracle (tests/test_pallas_digest.py)."""
    qs = jnp.asarray(qs, jnp.float32)
    lead = table.mean.shape[:-1]
    c = table.mean.shape[-1]
    m = table.mean.reshape((-1, c))
    w = table.weight.reshape((-1, c))
    mn = table.min.reshape((-1,))
    mx = table.max.reshape((-1,))
    from veneur_tpu.ops import pallas_digest
    if pallas_digest.enabled():
        flat = pallas_digest.quantiles_rows(m, w, mn, mx, qs)
    else:
        flat = jax.vmap(_quantiles_one, in_axes=(0, 0, 0, 0, None))(
            m, w, mn, mx, qs)
    return flat.reshape(lead + (qs.shape[0],))


def _cdf_one(mean, weight, mn, mx, xs_q):
    order = jnp.argsort(jnp.where(weight > 0, mean, jnp.inf))
    m = mean[order]
    w = jnp.where(weight[order] > 0, weight[order], 0.0)
    tot = jnp.sum(w)
    cum = jnp.cumsum(w)
    mid = cum - 0.5 * w
    xs = jnp.where(w > 0, m, mx)
    ys = jnp.where(w > 0, mid, tot)
    xs = jnp.concatenate([mn[None], xs, mx[None]])
    ys = jnp.concatenate([jnp.zeros((1,), ys.dtype), ys, tot[None]])
    out = jnp.interp(xs_q, xs, ys) / jnp.maximum(tot, 1e-30)
    return jnp.where(tot > 0, out, jnp.float32(jnp.nan))


def cdf(table: TDigestTable, xs) -> jax.Array:
    """CDF at points xs for every digest: returns f32[..., len(xs)]."""
    xs = jnp.asarray(xs, jnp.float32)
    lead = table.mean.shape[:-1]
    flat = jax.vmap(_cdf_one, in_axes=(0, 0, 0, 0, None))(
        table.mean.reshape((-1, table.mean.shape[-1])),
        table.weight.reshape((-1, table.weight.shape[-1])),
        table.min.reshape((-1,)), table.max.reshape((-1,)), xs)
    return flat.reshape(lead + (xs.shape[0],))


@partial(jax.jit,
         static_argnames=("compression", "cells_per_k", "exact_extremes"))
def add_batch_single(table: TDigestTable, values, weights, *,
                     compression: float = DEFAULT_COMPRESSION,
                     cells_per_k: int = DEFAULT_CELLS_PER_K,
                     exact_extremes: int = DEFAULT_EXACT_EXTREMES
                     ) -> TDigestTable:
    """Add a batch of samples to a SINGLE digest (table with scalar key shape ()).

    Used for tests and small-scale paths; the key-table ingest in
    aggregation/step.py handles the many-keys case.
    """
    values = jnp.asarray(values, jnp.float32)
    weights = jnp.asarray(weights, jnp.float32)
    out_c = table.mean.shape[-1]
    m = jnp.concatenate([table.mean, values], axis=-1)
    w = jnp.concatenate([table.weight, weights], axis=-1)
    m2, w2 = compress_rows(m[None, :], w[None, :], compression=compression,
                           cells_per_k=cells_per_k, out_c=out_c,
                           exact_extremes=exact_extremes)
    live = weights > 0
    vmasked = jnp.where(live, values, jnp.inf)
    ch, cl = table.count_hi, table.count_lo
    sh, sl = table.sum_hi, table.sum_lo
    rh, rl = table.recip_hi, table.recip_lo
    ch, cl = twofloat_add(ch, cl, jnp.sum(weights))
    sh, sl = twofloat_add(sh, sl, jnp.sum(jnp.where(live, weights * values, 0.0)))
    rh, rl = twofloat_add(rh, rl, jnp.sum(jnp.where(live, weights / jnp.where(live, values, 1.0), 0.0)))
    return TDigestTable(
        mean=m2[0], weight=w2[0],
        min=jnp.minimum(table.min, jnp.min(vmasked)),
        max=jnp.maximum(table.max, jnp.max(jnp.where(live, values, -jnp.inf))),
        count_hi=ch, count_lo=cl, sum_hi=sh, sum_lo=sl,
        recip_hi=rh, recip_lo=rl)
