"""Pallas TPU kernel for t-digest quantiles: per-row bitonic sort +
prefix-sum + piecewise-linear interpolation fused in VMEM.

The XLA path (ops/tdigest.py quantiles) lowers to a generic variadic
sort, a gather, and several elementwise passes — each a round-trip
through HBM over the [rows, cells] arrays. Rows are independent and a
row (512 cells after padding at the production 472-column layout) fits
comfortably in VMEM, so the whole reduction is one kernel: load a tile
of rows, sort each row's
(mean, weight) pairs with a fixed bitonic network (static shapes — the
digest's cell count is compile-time), cumsum, and evaluate the midpoint
interpolation for every requested quantile without ever leaving VMEM.

The sort is the standard vectorized bitonic network, its
compare-exchange expressed with static circular shifts + iota masks
(no dynamic indexing — Pallas/TPU wants static addressing),
~log²(C)/2 vectorized passes over the tile.
Interpolation avoids gathers entirely: for each quantile, every
adjacent centroid interval computes its candidate value and a one-hot
interval mask selects the right one (VPU-friendly mask+reduce).

Used by ops/tdigest.quantiles when `enabled()` — a real TPU backend
that passes a one-time probe compile (the tunneled dev platform is
experimental; a probe failure falls back to the XLA path rather than
breaking every flush). Force with VENEUR_TPU_PALLAS=1/0. Parity with
the XLA path is asserted bit-tolerantly in tests/test_pallas_digest.py
using interpret mode, which runs the same kernel on CPU.

Mosaic-lowering status (probed live on the tunneled chip, 2026-07-31):
this kernel now contains only primitives Mosaic accepts — jnp.cumsum
has no TC lowering (replaced by _prefix_sum_last) and the textbook
[..., C/2j, 2, j] compare-exchange reshape is rejected as an
interleaved vector reshape (replaced by rot+mask exchange). The dev
tunnel's verdict stays `false` for a different reason: its Pallas
compile service never returned within 400s even for a minimal
elementwise kernel, so the probe's 60s budget correctly degrades
production to the XLA path there. On a directly-attached TPU the
lowering blockers are gone.

Reference behavioral contract: merging_digest.go:302 Quantile (midpoint
interpolation between centroid masses, min/max endpoints).
"""

from __future__ import annotations

import functools
import logging
import os
import sys

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

log = logging.getLogger("veneur_tpu.ops.pallas_digest")

# rows per grid step at ≤256 cells; quantiles_rows halves this beyond
# 256 padded cells so the [tile, c_pad] f32 working set (inputs + sort
# temporaries) stays ~constant (≈0.5MB/array) as rows widen
ROW_TILE = 256


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _bitonic_sort_pairs(key, val):
    """Sort (key, val) rows ascending by key along the last axis with a
    bitonic network. Static shapes only: last dim must be a power of two.
    key/val: f32[..., C].

    The compare-exchange is expressed with static circular shifts plus
    iota masks rather than the textbook reshape to [..., C/2j, 2, j]:
    Mosaic rejects those interleaved vector reshapes on real TPU
    (`tpu.reshape vector<256x128xf32> -> vector<256x64x2x1xf32>`), while
    concat-slices and elementwise selects lower cleanly. Each position i
    fetches its partner i^j via a shift of +-j (partner pairs never
    wrap: i|j < C), then keeps min or max per the block direction."""
    c = key.shape[-1]
    pos = jax.lax.broadcasted_iota(jnp.int32, key.shape, key.ndim - 1)

    def rot(x, j):
        # circular left shift by j: position i sees x[(i+j) % C]
        return jnp.concatenate([x[..., j:], x[..., :j]], axis=-1)

    k = 2
    while k <= c:
        log2k = k.bit_length() - 1
        j = k // 2
        while j >= 1:
            is_lo = (pos & j) == 0                # partner is at i + j
            pk = jnp.where(is_lo, rot(key, j), rot(key, c - j))
            pv = jnp.where(is_lo, rot(val, j), rot(val, c - j))
            asc = ((pos >> log2k) & 1) == 0       # direction per k-block
            keep_min = asc == is_lo
            take = jnp.where(keep_min, pk < key, pk > key)
            key = jnp.where(take, pk, key)
            val = jnp.where(take, pv, val)
            j //= 2
        k *= 2
    return key, val


def _prefix_sum_last(x):
    """Inclusive prefix sum along the last axis via log-step shift-adds
    (Hillis-Steele): ceil(log2 C) static concat+slice passes instead of
    jnp.cumsum,
    whose primitive has no Mosaic TPU lowering (the probe used to die
    with `Unimplemented primitive ... cumsum`). Shapes are static, so
    every shift is a compile-time slice the VPU vectorizes."""
    c = x.shape[-1]
    zeros = jnp.zeros_like(x)
    d = 1
    while d < c:
        shifted = jnp.concatenate(
            [zeros[..., :d], x[..., :c - d]], axis=-1)
        x = x + shifted
        d *= 2
    return x


def _quantile_kernel(qs_ref, m_ref, w_ref, mn_ref, mx_ref, out_ref,
                     *, n_q: int):
    m = m_ref[...]                                   # [T, C]
    w = w_ref[...]
    mn = mn_ref[...]                                 # [T, 1]
    mx = mx_ref[...]
    live = w > 0
    key = jnp.where(live, m, jnp.float32(jnp.inf))
    skey, sw = _bitonic_sort_pairs(key, jnp.where(live, w, 0.0))
    tot = jnp.sum(sw, axis=-1, keepdims=True)        # [T, 1]
    cum = _prefix_sum_last(sw)
    mid = cum - 0.5 * sw
    # breakpoints: xs = [0, mid_0..mid_{C-1}, tot], ys = [min, mean.., max]
    # (empty cells collapse onto (tot, max): identical to the XLA path)
    occupied = sw > 0
    xs = jnp.where(occupied, mid, tot)
    ys = jnp.where(occupied, skey, mx)
    # interval breakpoints are quantile-invariant: build the segment
    # tables once, only t/inside/seg vary per quantile
    x_lo = jnp.concatenate([jnp.zeros_like(tot), xs], axis=-1)
    x_hi = jnp.concatenate([xs, tot], axis=-1)
    y_lo = jnp.concatenate([mn, ys], axis=-1)
    y_hi = jnp.concatenate([ys, mx], axis=-1)
    denom = jnp.maximum(x_hi - x_lo, jnp.float32(1e-30))
    slope = (y_hi - y_lo) / denom
    for qi in range(n_q):
        t = qs_ref[qi] * tot                         # [T, 1]
        # interval [xs_k, xs_{k+1}) containing t, plus the two endpoint
        # segments; one-hot masks instead of a gather
        seg = y_lo + (t - x_lo) * slope
        inside = (t >= x_lo) & (t < x_hi)
        # t == tot falls outside every half-open interval: clamp to max
        any_inside = jnp.any(inside, axis=-1, keepdims=True)
        picked = jnp.sum(jnp.where(inside, seg, 0.0), axis=-1,
                         keepdims=True)
        # degenerate intervals (duplicate xs) can double-select; divide
        # by the selection count to keep the value (all dups are equal)
        n_sel = jnp.maximum(
            jnp.sum(inside.astype(jnp.float32), axis=-1, keepdims=True),
            1.0)
        v = jnp.where(any_inside, picked / n_sel, mx)
        v = jnp.where(tot > 0, v, jnp.float32(jnp.nan))
        out_ref[:, qi:qi + 1] = v


def quantiles_rows(mean, weight, mn, mx, qs, *, interpret: bool = False):
    """Pallas quantiles over rows: mean/weight f32[R, C], mn/mx f32[R],
    qs f32[Q] -> f32[R, Q]. R is padded to a ROW_TILE multiple and C to
    a power of two (pad cells carry weight 0)."""
    r, c = mean.shape
    n_q = int(qs.shape[0])
    c_pad = max(_next_pow2(c), 128)
    # Keep the per-step VMEM working set roughly constant as the cell
    # count grows (exact-extreme protection widened production rows to
    # 472 → c_pad 512): halve the row tile beyond 256 cells so the sort
    # temporaries stay well inside VMEM on first-silicon runs.
    row_tile = ROW_TILE if c_pad <= 256 else ROW_TILE // 2
    r_pad = ((r + row_tile - 1) // row_tile) * row_tile
    if c_pad != c or r_pad != r:
        mean = jnp.pad(mean, ((0, r_pad - r), (0, c_pad - c)))
        weight = jnp.pad(weight, ((0, r_pad - r), (0, c_pad - c)))
        mn = jnp.pad(mn, (0, r_pad - r))
        mx = jnp.pad(mx, (0, r_pad - r))
    grid = (r_pad // row_tile,)
    out = pl.pallas_call(
        functools.partial(_quantile_kernel, n_q=n_q),
        grid=grid,
        in_specs=[
            pl.BlockSpec((n_q,), lambda i: (0,)),
            pl.BlockSpec((row_tile, c_pad), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, c_pad), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((row_tile, n_q), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((r_pad, n_q), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(qs, jnp.float32), mean, weight,
      mn.reshape(-1, 1), mx.reshape(-1, 1))
    return out[:r]


_PROBE_RESULT = None


def enabled() -> bool:
    """Use the Pallas path? VENEUR_TPU_PALLAS=1/0 forces; default is a
    one-time probe compile on the real-TPU backend (the dev tunnel's
    Pallas lowering is experimental — a broken lowering must degrade to
    the XLA path, not break every flush)."""
    global _PROBE_RESULT
    force = os.environ.get("VENEUR_TPU_PALLAS", "")
    if force == "1":
        return True
    if force == "0":
        return False
    if _PROBE_RESULT is None:
        try:
            if jax.devices()[0].platform == "cpu":
                _PROBE_RESULT = False
            else:
                _PROBE_RESULT = _run_probe_bounded()
        except Exception as e:  # noqa: BLE001 — any failure => XLA path
            log.warning("pallas quantile kernel unavailable, using XLA "
                        "path: %s", e)
            _PROBE_RESULT = False
    return _PROBE_RESULT


def _probe() -> bool:
    """Probe the PRODUCTION calling contexts, not just the standalone
    kernel: the flush paths run this under jit (and the sharded merge
    under vmap inside shard_map), where a missing pallas batching/
    lowering rule fails at outer compile time — that failure must land
    here, not in the first real flush."""
    def call(m, w, mn, mx):
        return quantiles_rows(m, w, mn, mx,
                              jnp.asarray([0.5], jnp.float32))

    m = jnp.asarray([[1.0, 2.0, 3.0, 4.0]], jnp.float32)
    w = jnp.ones((1, 4), jnp.float32)
    mn = jnp.asarray([1.0], jnp.float32)
    mx = jnp.asarray([4.0], jnp.float32)
    out = jax.jit(call)(m, w, mn, mx)
    out_v = jax.jit(jax.vmap(call))(m[None], w[None], mn[None], mx[None])
    # exact answer is 2.5 (midpoint interpolation between centroids 2
    # and 3); a loose tolerance would accept a miscompiled lowering
    # that returns a raw centroid
    return bool(abs(float(out[0, 0]) - 2.5) < 1e-3
                and abs(float(out_v[0, 0, 0]) - 2.5) < 1e-3)


def _run_probe_bounded(budget_s: float = 60.0) -> bool:
    """Run the probe in a SUBPROCESS with a hard budget. Two reasons for
    the process boundary: a wedged remote-compile service would
    otherwise stall the FIRST flush (the probe runs during its trace),
    and a timed-out in-process thread abandoned inside the JAX runtime
    aborts the interpreter at teardown (the rc-134 failure mode
    server.shutdown documents). A killed child leaks nothing, and with
    JAX_COMPILATION_CACHE_DIR set (bench.py does) the child's compile
    even seeds this process's cache. Operators running a flush watchdog
    tighter than this budget should pin VENEUR_TPU_PALLAS=0/1 instead
    of relying on the probe."""
    import subprocess
    code = ("import sys; sys.path.insert(0, %r); "
            "from veneur_tpu.ops.pallas_digest import _probe; "
            "print('PALLAS_OK' if _probe() else 'PALLAS_NO')"
            % os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=budget_s)
    except subprocess.TimeoutExpired:
        log.warning("pallas probe exceeded %.0fs (compile service "
                    "stalled?); using XLA path", budget_s)
        return False
    ok = "PALLAS_OK" in proc.stdout
    if not ok:
        log.warning("pallas quantile kernel unavailable, using XLA path "
                    "(probe rc=%d)", proc.returncode)
    return ok
