from veneur_tpu.ops import hll, tdigest

__all__ = ["hll", "tdigest"]
