"""Batched HyperLogLog for TPU.

The reference's Set sampler holds one axiomhq/hyperloglog sketch (2^14
registers) per set key and does Insert / Merge(union = register max) /
Estimate (reference samplers/samplers.go:367-463). Here a batch of sketches is
one uint8 array [..., R]:

- insert: the host hashes the member string to 64 bits with MetroHash64
  seed 1337 — the exact member hash of the reference's vendored sketch, so
  sketches union correctly across a mixed fleet — and ships
  (register_index, rho) pairs; the device does a deduplicated
  scatter-max (sort by register → segment-max → unique-index scatter),
- merge/union: elementwise ``maximum`` — which over a device mesh is exactly
  ``lax.pmax``, making the reference's global set-union (worker.go:438-495
  ImportMetricGRPC → Set.Merge) a single ICI collective,
- estimate: the classic HLL harmonic-mean estimator with linear counting for
  the small range, vectorized over keys.

Precision p=14 (R=16384) matches the reference's default
(samplers/samplers.go:383).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

DEFAULT_PRECISION = 14


def num_registers(precision: int = DEFAULT_PRECISION) -> int:
    return 1 << precision


def empty_registers(key_shape, precision: int = DEFAULT_PRECISION) -> jax.Array:
    key_shape = (key_shape,) if isinstance(key_shape, int) else tuple(key_shape)
    return jnp.zeros(key_shape + (num_registers(precision),), jnp.uint8)


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def split_hash(hashes64, precision: int = DEFAULT_PRECISION):
    """Host-side helper: split uint64 hashes (as a numpy/int array) into
    (register index, rho) — rho = 1 + leading-zero-count of the remaining
    64-p bits, capped at 64-p+1."""
    import numpy as np
    h = np.asarray(hashes64, dtype=np.uint64)
    p = precision
    reg = (h >> np.uint64(64 - p)).astype(np.int32)
    rest = h << np.uint64(p)  # top 64-p payload bits in the high positions
    # rho = leading zeros of rest (within 64-p bits) + 1
    rho = np.zeros(h.shape, np.int32)
    cur = rest
    # binary leading-zero count on uint64
    lz = np.full(h.shape, 0, np.int32)
    for shift in (32, 16, 8, 4, 2, 1):
        mask = cur < (np.uint64(1) << np.uint64(64 - shift))
        lz = np.where(mask, lz + shift, lz)
        cur = np.where(mask, cur << np.uint64(shift), cur)
    lz = np.where(rest == 0, 64, lz)
    rho = np.minimum(lz, 64 - p) + 1
    return reg, rho.astype(np.uint8)


@partial(jax.jit, static_argnames=("precision",))
def insert_batch(registers, slot, reg, rho, *, precision: int = DEFAULT_PRECISION):
    """Scatter-max a batch of (slot, register, rho) into registers [K, R].

    slot: i32[B] key-table slot (slot >= K → dropped padding),
    reg:  i32[B] register index in [0, R),
    rho:  u8[B] rank value.

    Dedup first (sort by flat index, segment-max) so the final scatter has
    unique indices — the fast path on TPU.
    """
    k = registers.shape[0]
    # 2D scatter indices (slot, reg) — avoids int32 overflow of a flattened
    # slot*R+reg index for large key tables (K*R can exceed 2^31).
    slot = jnp.where((slot >= 0) & (slot < k), slot, k)
    order = jnp.lexsort((reg, slot))
    ss = slot[order]
    gs = reg[order]
    rs = rho[order]
    same = (ss[:-1] == ss[1:]) & (gs[:-1] == gs[1:])
    is_last = jnp.concatenate([~same, jnp.ones((1,), bool)])
    # running max within runs of equal (slot, reg)
    seg_start = jnp.concatenate([jnp.ones((1,), bool), ~same])
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    run_max = jax.ops.segment_max(rs.astype(jnp.int32), seg_id,
                                  num_segments=slot.shape[0],
                                  indices_are_sorted=True)
    upd_slot = jnp.where(is_last, ss, k)
    upd_val = run_max[seg_id].astype(jnp.uint8)
    return registers.at[upd_slot, gs].max(jnp.where(is_last, upd_val, 0),
                                          mode="drop")


def merge(a, b):
    """Union of two register tables (reference Set.Merge, samplers.go:461)."""
    return jnp.maximum(a, b)


@jax.jit
def merge_rows(registers, slot, rows):
    """Scatter-union imported register rows into a table: the global-tier
    HLL merge (reference worker.go:438 ImportMetricGRPC -> Set.Merge).
    registers u8[K, R], slot i32[B] (out-of-range = drop), rows u8[B, R]."""
    return registers.at[slot].max(rows, mode="drop")


MAGIC = b"VHLL"          # legacy round-1 wire format (still decodable)
_SPARSE_PP = 25          # axiomhq sparse precision (hyperloglog.go pp)


def serialize(registers, precision: int = DEFAULT_PRECISION) -> bytes:
    """Wire bytes for one key's registers in the reference sketch's
    MarshalBinary layout (axiomhq/hyperloglog hyperloglog.go:274): dense
    form `[version=1][p][b][sparse=0][len(m/2) BE32][m/2 nibble-packed
    bytes]`, register value = b + stored nibble, register 2i in the high
    nibble of byte i. A reference global can UnmarshalBinary these bytes
    directly, so forwarded set metrics merge across a mixed fleet.

    Base selection mirrors the reference's rebase invariant (b only ever
    grows to the register minimum): exact whenever the register spread fits
    in a nibble, saturating at b+15 otherwise — the same tailcut loss the
    reference's own insert applies (hyperloglog.go:169-180).
    """
    import numpy as np
    regs = np.asarray(registers, np.uint8)
    m = regs.shape[0]
    mn, mx = int(regs.min()), int(regs.max())
    b = 0
    if mn > 0 and mx > 15:
        b = min(mn, mx - 15)
    stored = np.clip(regs.astype(np.int32) - b, 0, 15).astype(np.uint8)
    packed = ((stored[0::2] << 4) | stored[1::2]).astype(np.uint8)
    return (bytes([1, precision, b, 0]) + (m // 2).to_bytes(4, "big")
            + packed.tobytes())


def _decode_sparse_hash(k: int, p: int):
    """axiomhq sparse.go decodeHash: sparse key -> (register, rho)."""
    pp = _SPARSE_PP
    if k & 1:
        r = ((k >> 1) & 0x3F) + pp - p
        idx = (k >> (32 - p)) & ((1 << p) - 1)
    else:
        shifted = (k << (32 - pp + p - 1)) & 0xFFFFFFFF
        # clz32(shifted) + 1; shifted==0 cannot occur for a valid key
        r = (33 - shifted.bit_length()) if shifted else 32
        idx = (k >> (pp - p + 1)) & ((1 << p) - 1)
    return idx, r


def _deserialize_axiomhq(data: bytes):
    import numpy as np
    p = data[1]
    b = data[2]
    m = 1 << p
    if data[3] == 1:
        # sparse form: tmpSet (BE32 count + BE32 keys) then compressedList
        # (count, last, varint-delta list) — decode into dense registers,
        # exactly the sketch's own toNormal() conversion
        regs = np.zeros(m, np.uint8)
        (tssz,) = _be32(data, 4)
        if 8 + 4 * tssz + 12 > len(data):
            raise ValueError("truncated HLL sparse payload (tmpSet)")
        off = 8
        keys = []
        for _ in range(tssz):
            keys.append(int.from_bytes(data[off:off + 4], "big"))
            off += 4
        off += 8  # compressedList count + last (we re-derive from deltas)
        (sz,) = _be32(data, off)
        off += 4
        if off + sz > len(data):
            raise ValueError("truncated HLL sparse payload (list)")
        buf = data[off:off + sz]
        i, last = 0, 0
        while i < len(buf):
            x, j = 0, i
            while buf[j] & 0x80:
                x |= (buf[j] & 0x7F) << ((j - i) * 7)
                j += 1
                if j >= len(buf):
                    raise ValueError("truncated HLL sparse varint")
            x |= buf[j] << ((j - i) * 7)
            last += x
            keys.append(last)
            i = j + 1
        for k in keys:
            idx, r = _decode_sparse_hash(k, p)
            if r > regs[idx]:
                regs[idx] = r
        return p, regs
    (sz,) = _be32(data, 4)
    packed = np.frombuffer(data[8:8 + sz], np.uint8)
    if packed.shape[0] != m // 2:
        raise ValueError("HLL dense payload length mismatch")
    regs = np.empty(m, np.uint8)
    regs[0::2] = packed >> 4
    regs[1::2] = packed & 0x0F
    if b:
        regs = (regs.astype(np.int32) + b).astype(np.uint8)
    return p, regs


def _be32(data: bytes, off: int):
    return (int.from_bytes(data[off:off + 4], "big"),)


def deserialize(data: bytes):
    """Parse sketch wire bytes -> (precision, uint8 registers[2^p]).

    Accepts the reference's axiomhq MarshalBinary bytes (dense AND sparse
    forms) and this framework's legacy VHLL dump."""
    import numpy as np
    if data[:4] == MAGIC:
        precision = data[4]
        regs = np.frombuffer(data[5:], np.uint8)
        if regs.shape[0] != (1 << precision):
            raise ValueError("HLL payload length mismatch")
        return precision, regs
    if len(data) >= 8 and data[0] == 1 and 4 <= data[1] <= 18:
        return _deserialize_axiomhq(data)
    raise ValueError("unrecognized HLL payload")


@partial(jax.jit, static_argnames=("precision",))
def estimate(registers, *, precision: int = DEFAULT_PRECISION):
    """Cardinality estimate per key: f32[...] over registers [..., R].

    Classic HLL: alpha·m²/Σ2^-M_j, with linear counting m·ln(m/V) when the
    raw estimate is below 5/2·m and zero registers exist. The reference's
    vendored lib uses the LogLog-Beta variant; both sit inside the ~0.8%
    standard error at p=14, which is what the tests assert.
    """
    m = num_registers(precision)
    regs = registers.astype(jnp.float32)
    inv = jnp.sum(jnp.exp2(-regs), axis=-1)
    raw = _alpha(m) * m * m / inv
    zeros = jnp.sum((registers == 0).astype(jnp.float32), axis=-1)
    lin = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_lin = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_lin, lin, raw)
