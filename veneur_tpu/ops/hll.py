"""Batched HyperLogLog for TPU.

The reference's Set sampler holds one axiomhq/hyperloglog sketch (2^14
registers) per set key and does Insert / Merge(union = register max) /
Estimate (reference samplers/samplers.go:367-463). Here a batch of sketches is
one uint8 array [..., R]:

- insert: the host hashes the member string to 64 bits (metrohash in the
  reference's vendored lib; we use xxhash-style splitmix on the host) and
  ships (register_index, rho) pairs; the device does a deduplicated
  scatter-max (sort by register → segment-max → unique-index scatter),
- merge/union: elementwise ``maximum`` — which over a device mesh is exactly
  ``lax.pmax``, making the reference's global set-union (worker.go:438-495
  ImportMetricGRPC → Set.Merge) a single ICI collective,
- estimate: the classic HLL harmonic-mean estimator with linear counting for
  the small range, vectorized over keys.

Precision p=14 (R=16384) matches the reference's default
(samplers/samplers.go:383).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

DEFAULT_PRECISION = 14


def num_registers(precision: int = DEFAULT_PRECISION) -> int:
    return 1 << precision


def empty_registers(key_shape, precision: int = DEFAULT_PRECISION) -> jax.Array:
    key_shape = (key_shape,) if isinstance(key_shape, int) else tuple(key_shape)
    return jnp.zeros(key_shape + (num_registers(precision),), jnp.uint8)


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def split_hash(hashes64, precision: int = DEFAULT_PRECISION):
    """Host-side helper: split uint64 hashes (as a numpy/int array) into
    (register index, rho) — rho = 1 + leading-zero-count of the remaining
    64-p bits, capped at 64-p+1."""
    import numpy as np
    h = np.asarray(hashes64, dtype=np.uint64)
    p = precision
    reg = (h >> np.uint64(64 - p)).astype(np.int32)
    rest = h << np.uint64(p)  # top 64-p payload bits in the high positions
    # rho = leading zeros of rest (within 64-p bits) + 1
    rho = np.zeros(h.shape, np.int32)
    cur = rest
    # binary leading-zero count on uint64
    lz = np.full(h.shape, 0, np.int32)
    for shift in (32, 16, 8, 4, 2, 1):
        mask = cur < (np.uint64(1) << np.uint64(64 - shift))
        lz = np.where(mask, lz + shift, lz)
        cur = np.where(mask, cur << np.uint64(shift), cur)
    lz = np.where(rest == 0, 64, lz)
    rho = np.minimum(lz, 64 - p) + 1
    return reg, rho.astype(np.uint8)


@partial(jax.jit, static_argnames=("precision",))
def insert_batch(registers, slot, reg, rho, *, precision: int = DEFAULT_PRECISION):
    """Scatter-max a batch of (slot, register, rho) into registers [K, R].

    slot: i32[B] key-table slot (slot >= K → dropped padding),
    reg:  i32[B] register index in [0, R),
    rho:  u8[B] rank value.

    Dedup first (sort by flat index, segment-max) so the final scatter has
    unique indices — the fast path on TPU.
    """
    k = registers.shape[0]
    # 2D scatter indices (slot, reg) — avoids int32 overflow of a flattened
    # slot*R+reg index for large key tables (K*R can exceed 2^31).
    slot = jnp.where((slot >= 0) & (slot < k), slot, k)
    order = jnp.lexsort((reg, slot))
    ss = slot[order]
    gs = reg[order]
    rs = rho[order]
    same = (ss[:-1] == ss[1:]) & (gs[:-1] == gs[1:])
    is_last = jnp.concatenate([~same, jnp.ones((1,), bool)])
    # running max within runs of equal (slot, reg)
    seg_start = jnp.concatenate([jnp.ones((1,), bool), ~same])
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    run_max = jax.ops.segment_max(rs.astype(jnp.int32), seg_id,
                                  num_segments=slot.shape[0],
                                  indices_are_sorted=True)
    upd_slot = jnp.where(is_last, ss, k)
    upd_val = run_max[seg_id].astype(jnp.uint8)
    return registers.at[upd_slot, gs].max(jnp.where(is_last, upd_val, 0),
                                          mode="drop")


def merge(a, b):
    """Union of two register tables (reference Set.Merge, samplers.go:461)."""
    return jnp.maximum(a, b)


@jax.jit
def merge_rows(registers, slot, rows):
    """Scatter-union imported register rows into a table: the global-tier
    HLL merge (reference worker.go:438 ImportMetricGRPC -> Set.Merge).
    registers u8[K, R], slot i32[B] (out-of-range = drop), rows u8[B, R]."""
    return registers.at[slot].max(rows, mode="drop")


MAGIC = b"VHLL"


def serialize(registers, precision: int = DEFAULT_PRECISION) -> bytes:
    """Forwarding bytes for one key's registers (this framework's wire
    format for metricpb.SetValue.hyper_log_log; the reference ships
    axiomhq/hyperloglog MarshalBinary, which is implementation-defined —
    sketch bytes only interoperate between same-impl tiers)."""
    import numpy as np
    return MAGIC + bytes([precision]) + np.asarray(registers, np.uint8).tobytes()


def deserialize(data: bytes):
    import numpy as np
    if data[:4] != MAGIC:
        raise ValueError("bad HLL payload")
    precision = data[4]
    regs = np.frombuffer(data[5:], np.uint8)
    if regs.shape[0] != (1 << precision):
        raise ValueError("HLL payload length mismatch")
    return precision, regs


@partial(jax.jit, static_argnames=("precision",))
def estimate(registers, *, precision: int = DEFAULT_PRECISION):
    """Cardinality estimate per key: f32[...] over registers [..., R].

    Classic HLL: alpha·m²/Σ2^-M_j, with linear counting m·ln(m/V) when the
    raw estimate is below 5/2·m and zero registers exist. The reference's
    vendored lib uses the LogLog-Beta variant; both sit inside the ~0.8%
    standard error at p=14, which is what the tests assert.
    """
    m = num_registers(precision)
    regs = registers.astype(jnp.float32)
    inv = jnp.sum(jnp.exp2(-regs), axis=-1)
    raw = _alpha(m) * m * m / inv
    zeros = jnp.sum((registers == 0).astype(jnp.float32), axis=-1)
    lin = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_lin = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_lin, lin, raw)
