"""Batched HyperLogLog for TPU.

The reference's Set sampler holds one axiomhq/hyperloglog sketch (2^14
registers) per set key and does Insert / Merge(union = register max) /
Estimate (reference samplers/samplers.go:367-463). Here a batch of sketches is
one uint8 array [..., R]:

- insert: the host hashes the member string to 64 bits with MetroHash64
  seed 1337 — the exact member hash of the reference's vendored sketch, so
  sketches union correctly across a mixed fleet — and ships
  (register_index, rho) pairs; the device does a deduplicated
  scatter-max (sort by register → segment-max → unique-index scatter),
- merge/union: elementwise ``maximum`` — which over a device mesh is exactly
  ``lax.pmax``, making the reference's global set-union (worker.go:438-495
  ImportMetricGRPC → Set.Merge) a single ICI collective,
- estimate: the classic HLL harmonic-mean estimator with linear counting for
  the small range, vectorized over keys.

Precision p=14 (R=16384) matches the reference's default
(samplers/samplers.go:383).

Round 8 adds a 6-bit *packed* register layout (FPGA HLL pipelines,
PAPERS.md arxiv 2005.13332): register values never exceed 64-p+1 = 51
at p=14, so 6 bits suffice and the resident table shrinks from
``uint8[K, 2^p]`` to ``int32[K, ceil(2^p*6/32)]`` words — register r
lives at bit offset 6·r little-endian within the word stream. Because
2^p is a multiple of 16 the pattern repeats exactly every 16 registers
/ 3 words (96 bits), which is what `pack_registers`/`unpack_registers`
exploit and what guarantees a straddling register's second word always
exists (the last register of each 16-group starts at in-word bit 26).
The packed table is what the device holds and what the fused Pallas
ingest kernel updates in place; `estimate`/`serialize` accept either
layout, and wire bytes are unchanged — packing is an at-rest layout,
not a wire format.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

DEFAULT_PRECISION = 14


def num_registers(precision: int = DEFAULT_PRECISION) -> int:
    return 1 << precision


def empty_registers(key_shape, precision: int = DEFAULT_PRECISION) -> jax.Array:
    key_shape = (key_shape,) if isinstance(key_shape, int) else tuple(key_shape)
    return jnp.zeros(key_shape + (num_registers(precision),), jnp.uint8)


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def split_hash(hashes64, precision: int = DEFAULT_PRECISION):
    """Host-side helper: split uint64 hashes (as a numpy/int array) into
    (register index, rho) — rho = 1 + leading-zero-count of the remaining
    64-p bits, capped at 64-p+1."""
    import numpy as np
    h = np.asarray(hashes64, dtype=np.uint64)
    p = precision
    reg = (h >> np.uint64(64 - p)).astype(np.int32)
    rest = h << np.uint64(p)  # top 64-p payload bits in the high positions
    # rho = leading zeros of rest (within 64-p bits) + 1
    rho = np.zeros(h.shape, np.int32)
    cur = rest
    # binary leading-zero count on uint64
    lz = np.full(h.shape, 0, np.int32)
    for shift in (32, 16, 8, 4, 2, 1):
        mask = cur < (np.uint64(1) << np.uint64(64 - shift))
        lz = np.where(mask, lz + shift, lz)
        cur = np.where(mask, cur << np.uint64(shift), cur)
    lz = np.where(rest == 0, 64, lz)
    rho = np.minimum(lz, 64 - p) + 1
    return reg, rho.astype(np.uint8)


@partial(jax.jit, static_argnames=("precision",))
def insert_batch(registers, slot, reg, rho, *, precision: int = DEFAULT_PRECISION):
    """Scatter-max a batch of (slot, register, rho) into registers [K, R].

    slot: i32[B] key-table slot (slot >= K → dropped padding),
    reg:  i32[B] register index in [0, R),
    rho:  u8[B] rank value.

    Dedup first (sort by flat index, segment-max) so the final scatter has
    unique indices — the fast path on TPU.
    """
    k = registers.shape[0]
    # 2D scatter indices (slot, reg) — avoids int32 overflow of a flattened
    # slot*R+reg index for large key tables (K*R can exceed 2^31).
    slot = jnp.where((slot >= 0) & (slot < k), slot, k)
    order = jnp.lexsort((reg, slot))
    ss = slot[order]
    gs = reg[order]
    rs = rho[order]
    same = (ss[:-1] == ss[1:]) & (gs[:-1] == gs[1:])
    is_last = jnp.concatenate([~same, jnp.ones((1,), bool)])
    # running max within runs of equal (slot, reg)
    seg_start = jnp.concatenate([jnp.ones((1,), bool), ~same])
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    run_max = jax.ops.segment_max(rs.astype(jnp.int32), seg_id,
                                  num_segments=slot.shape[0],
                                  indices_are_sorted=True)
    upd_slot = jnp.where(is_last, ss, k)
    upd_val = run_max[seg_id].astype(jnp.uint8)
    return registers.at[upd_slot, gs].max(jnp.where(is_last, upd_val, 0),
                                          mode="drop")


def merge(a, b):
    """Union of two register tables (reference Set.Merge, samplers.go:461)."""
    return jnp.maximum(a, b)


@jax.jit
def merge_rows(registers, slot, rows):
    """Scatter-union imported register rows into a table: the global-tier
    HLL merge (reference worker.go:438 ImportMetricGRPC -> Set.Merge).
    registers u8[K, R], slot i32[B] (out-of-range = drop), rows u8[B, R]."""
    return registers.at[slot].max(rows, mode="drop")


# ---------------------------------------------------------------------------
# 6-bit packed register layout
# ---------------------------------------------------------------------------

REGISTER_BITS = 6        # max rho = 64-4+1 = 61 < 64 fits any p >= 4


def packed_words(precision: int = DEFAULT_PRECISION) -> int:
    """int32 words per key for the 6-bit packed layout."""
    return (num_registers(precision) * REGISTER_BITS + 31) // 32


def empty_registers_packed(key_shape,
                           precision: int = DEFAULT_PRECISION) -> jax.Array:
    key_shape = (key_shape,) if isinstance(key_shape, int) else tuple(key_shape)
    return jnp.zeros(key_shape + (packed_words(precision),), jnp.int32)


def _group16(x, last):
    """Reshape the trailing axis into (groups, last) 16-register groups.
    The group count is computed explicitly (not -1): a zero-row input —
    e.g. restoring a snapshot with no live sets — makes -1 unresolvable."""
    return x.reshape(x.shape[:-1] + (x.shape[-1] // last, last))


def pack_registers(regs, *, precision: int = DEFAULT_PRECISION) -> jax.Array:
    """u8[..., R] dense registers -> i32[..., W] 6-bit packed words.

    16 registers pack into exactly 3 words (96 bits), so the whole
    transform is shifts and ORs over a [..., R/16, 16] view — no scatter.
    Left shifts that cross bit 31 wrap (defined for lax shifts); the bit
    pattern is what matters.
    """
    r = num_registers(precision)
    assert r % 16 == 0 and regs.shape[-1] == r
    v = _group16(regs, 16).astype(jnp.int32) & 0x3F
    g = [v[..., i] for i in range(16)]
    w0 = (g[0] | (g[1] << 6) | (g[2] << 12) | (g[3] << 18) | (g[4] << 24)
          | ((g[5] & 0x3) << 30))
    w1 = ((g[5] >> 2) | (g[6] << 4) | (g[7] << 10) | (g[8] << 16)
          | (g[9] << 22) | ((g[10] & 0xF) << 28))
    w2 = ((g[10] >> 4) | (g[11] << 2) | (g[12] << 8) | (g[13] << 14)
          | (g[14] << 20) | (g[15] << 26))
    words = jnp.stack([w0, w1, w2], axis=-1)
    return words.reshape(regs.shape[:-1] + (packed_words(precision),))


def unpack_registers(words, *, precision: int = DEFAULT_PRECISION) -> jax.Array:
    """i32[..., W] packed words -> u8[..., R] dense registers.

    Right shifts on int32 are arithmetic (sign-extending); every lane is
    masked after the shift, so the sign bit never leaks into a register.
    """
    w = packed_words(precision)
    assert words.shape[-1] == w
    g = _group16(words, 3)
    w0, w1, w2 = g[..., 0], g[..., 1], g[..., 2]
    regs = [
        w0 & 0x3F, (w0 >> 6) & 0x3F, (w0 >> 12) & 0x3F, (w0 >> 18) & 0x3F,
        (w0 >> 24) & 0x3F,
        ((w0 >> 30) & 0x3) | ((w1 & 0xF) << 2),
        (w1 >> 4) & 0x3F, (w1 >> 10) & 0x3F, (w1 >> 16) & 0x3F,
        (w1 >> 22) & 0x3F,
        ((w1 >> 28) & 0xF) | ((w2 & 0x3) << 4),
        (w2 >> 2) & 0x3F, (w2 >> 8) & 0x3F, (w2 >> 14) & 0x3F,
        (w2 >> 20) & 0x3F, (w2 >> 26) & 0x3F,
    ]
    out = jnp.stack(regs, axis=-1)
    return out.reshape(words.shape[:-1]
                       + (num_registers(precision),)).astype(jnp.uint8)


def pack_registers_np(regs, precision: int = DEFAULT_PRECISION):
    """Host numpy twin of pack_registers (persistence / import staging)."""
    import numpy as np
    regs = np.asarray(regs, np.uint8)
    r = num_registers(precision)
    assert r % 16 == 0 and regs.shape[-1] == r
    v = _group16(regs, 16).astype(np.int64) & 0x3F
    g = [v[..., i] for i in range(16)]
    w0 = (g[0] | (g[1] << 6) | (g[2] << 12) | (g[3] << 18) | (g[4] << 24)
          | ((g[5] & 0x3) << 30))
    w1 = ((g[5] >> 2) | (g[6] << 4) | (g[7] << 10) | (g[8] << 16)
          | (g[9] << 22) | ((g[10] & 0xF) << 28))
    w2 = ((g[10] >> 4) | (g[11] << 2) | (g[12] << 8) | (g[13] << 14)
          | (g[14] << 20) | (g[15] << 26))
    words = np.stack([w0, w1, w2], axis=-1) & 0xFFFFFFFF
    return (words.reshape(regs.shape[:-1] + (packed_words(precision),))
            .astype(np.uint32).view(np.int32))


def unpack_registers_np(words, precision: int = DEFAULT_PRECISION):
    """Host numpy twin of unpack_registers."""
    import numpy as np
    words = np.asarray(words)
    w = packed_words(precision)
    assert words.shape[-1] == w
    u = (words.astype(np.int64) & 0xFFFFFFFF)
    g = _group16(u, 3)
    w0, w1, w2 = g[..., 0], g[..., 1], g[..., 2]
    regs = [
        w0 & 0x3F, (w0 >> 6) & 0x3F, (w0 >> 12) & 0x3F, (w0 >> 18) & 0x3F,
        (w0 >> 24) & 0x3F,
        ((w0 >> 30) & 0x3) | ((w1 & 0xF) << 2),
        (w1 >> 4) & 0x3F, (w1 >> 10) & 0x3F, (w1 >> 16) & 0x3F,
        (w1 >> 22) & 0x3F,
        ((w1 >> 28) & 0xF) | ((w2 & 0x3) << 4),
        (w2 >> 2) & 0x3F, (w2 >> 8) & 0x3F, (w2 >> 14) & 0x3F,
        (w2 >> 20) & 0x3F, (w2 >> 26) & 0x3F,
    ]
    out = np.stack(regs, axis=-1)
    return out.reshape(words.shape[:-1]
                       + (num_registers(precision),)).astype(np.uint8)


@partial(jax.jit, static_argnames=("precision",))
def insert_batch_packed(words, slot, reg, rho, *,
                        precision: int = DEFAULT_PRECISION):
    """`insert_batch` over the packed table: unpack -> dense scatter-max ->
    repack. The XLA fallback path when the fused Pallas kernel is off; the
    round trip through the dense layout makes parity with `insert_batch`
    true by construction (register max commutes with packing)."""
    dense = unpack_registers(words, precision=precision)
    dense = insert_batch(dense, slot, reg, rho, precision=precision)
    return pack_registers(dense, precision=precision)


@partial(jax.jit, static_argnames=("precision",))
def merge_rows_packed(words, slot, rows, *,
                      precision: int = DEFAULT_PRECISION):
    """`merge_rows` over the packed table: union dense u8 import rows into
    i32 packed words. Touches only the B addressed rows (gather -> unpack
    -> max -> pack -> unique-index set), not the whole table. Duplicate
    slots are combined host-order-free by a segment-max before the set,
    so the final `.set` has unique indices. Out-of-range slots —
    including negative ones — are dropped."""
    k = words.shape[0]
    slot = jnp.where((slot >= 0) & (slot < k), slot, k)
    order = jnp.argsort(slot)
    ss = slot[order]
    rs = rows[order].astype(jnp.int32)
    seg_start = jnp.concatenate([jnp.ones((1,), bool), ss[1:] != ss[:-1]])
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    combined = jax.ops.segment_max(rs, seg_id, num_segments=slot.shape[0],
                                   indices_are_sorted=True)
    upd = combined[seg_id].astype(jnp.uint8)       # per-position segment max
    tgt = jnp.where(seg_start, ss, k)              # unique: segment heads only
    cur = words[jnp.minimum(tgt, k - 1)]           # dropped rows gather junk,
    #                                                never written back
    merged = jnp.maximum(unpack_registers(cur, precision=precision), upd)
    packed = pack_registers(merged, precision=precision)
    return words.at[tgt].set(packed, mode="drop")


MAGIC = b"VHLL"          # legacy round-1 wire format (still decodable)
_SPARSE_PP = 25          # axiomhq sparse precision (hyperloglog.go pp)


def serialize(registers, precision: int = DEFAULT_PRECISION) -> bytes:
    """Wire bytes for one key's registers in the reference sketch's
    MarshalBinary layout (axiomhq/hyperloglog hyperloglog.go:274): dense
    form `[version=1][p][b][sparse=0][len(m/2) BE32][m/2 nibble-packed
    bytes]`, register value = b + stored nibble, register 2i in the high
    nibble of byte i. A reference global can UnmarshalBinary these bytes
    directly, so forwarded set metrics merge across a mixed fleet.

    Base selection mirrors the reference's rebase invariant (b only ever
    grows to the register minimum): exact whenever the register spread fits
    in a nibble, saturating at b+15 otherwise — the same tailcut loss the
    reference's own insert applies (hyperloglog.go:169-180).
    """
    import numpy as np
    regs = np.asarray(registers)
    if regs.dtype != np.uint8:           # 6-bit packed i32 row
        regs = unpack_registers_np(regs, precision)
    m = regs.shape[0]
    mn, mx = int(regs.min()), int(regs.max())
    b = 0
    if mn > 0 and mx > 15:
        b = min(mn, mx - 15)
    stored = np.clip(regs.astype(np.int32) - b, 0, 15).astype(np.uint8)
    packed = ((stored[0::2] << 4) | stored[1::2]).astype(np.uint8)
    return (bytes([1, precision, b, 0]) + (m // 2).to_bytes(4, "big")
            + packed.tobytes())


def _decode_sparse_hash(k: int, p: int):
    """axiomhq sparse.go decodeHash: sparse key -> (register, rho)."""
    pp = _SPARSE_PP
    if k & 1:
        r = ((k >> 1) & 0x3F) + pp - p
        idx = (k >> (32 - p)) & ((1 << p) - 1)
    else:
        shifted = (k << (32 - pp + p - 1)) & 0xFFFFFFFF
        # clz32(shifted) + 1; shifted==0 cannot occur for a valid key
        r = (33 - shifted.bit_length()) if shifted else 32
        idx = (k >> (pp - p + 1)) & ((1 << p) - 1)
    return idx, r


def _bitlen32(x):
    """Vectorized int.bit_length for non-negative int64 arrays < 2^32.
    Binary-search halving — no float log2 (exact at every power of two)."""
    import numpy as np
    x = x.astype(np.int64)
    n = np.zeros_like(x)
    for s in (16, 8, 4, 2, 1):
        big = x >= (np.int64(1) << s)
        n = np.where(big, n + s, n)
        x = np.where(big, x >> s, x)
    return n + (x > 0)


def _decode_sparse_hashes_np(keys, p: int):
    """Vectorized `_decode_sparse_hash` over an int64 key array — returns
    (idx, r) int64 arrays. Same field math as the scalar version (the
    sparse-form oracle test in tests/test_hll.py pins both)."""
    import numpy as np
    pp = _SPARSE_PP
    k = keys.astype(np.int64) & 0xFFFFFFFF
    m = 1 << p
    odd = (k & 1) == 1
    r_odd = ((k >> 1) & 0x3F) + pp - p
    idx_odd = (k >> (32 - p)) & (m - 1)
    shifted = (k << (32 - pp + p - 1)) & 0xFFFFFFFF
    r_even = np.where(shifted == 0, 32, 33 - _bitlen32(shifted))
    idx_even = (k >> (pp - p + 1)) & (m - 1)
    return (np.where(odd, idx_odd, idx_even),
            np.where(odd, r_odd, r_even))


def _decode_varint_deltas(buf: bytes):
    """Vectorized LEB128 varint decode of axiomhq's compressedList delta
    stream -> int64 delta array. Replaces the per-byte Python while loop
    (round-8 satellite; ~40x on a 16k-key sparse payload — see
    benchmarks/micro.py hll_codec_roundtrip).

    Grouping trick: a varint ends at each byte with the continuation bit
    clear; `np.add.reduceat` over per-byte `7*pos`-shifted payloads at the
    group starts reassembles every value in one pass."""
    import numpy as np
    if not buf:
        return np.zeros(0, np.int64)
    b = np.frombuffer(buf, np.uint8).astype(np.int64)
    is_end = (b & 0x80) == 0
    if not is_end[-1]:
        raise ValueError("truncated HLL sparse varint")
    ends = np.nonzero(is_end)[0]
    starts = np.concatenate([[0], ends[:-1] + 1])
    gid = np.cumsum(np.concatenate([[False], is_end[:-1]]).astype(np.int64))
    pos = np.arange(b.shape[0]) - starts[gid]
    if pos.max() * 7 >= 63:
        raise ValueError("HLL sparse varint too long")
    vals = (b & 0x7F) << (7 * pos)
    return np.add.reduceat(vals, starts)


def _deserialize_axiomhq(data: bytes):
    import numpy as np
    p = data[1]
    b = data[2]
    m = 1 << p
    if data[3] == 1:
        # sparse form: tmpSet (BE32 count + BE32 keys) then compressedList
        # (count, last, varint-delta list) — decode into dense registers,
        # exactly the sketch's own toNormal() conversion
        regs = np.zeros(m, np.uint8)
        (tssz,) = _be32(data, 4)
        if 8 + 4 * tssz + 12 > len(data):
            raise ValueError("truncated HLL sparse payload (tmpSet)")
        off = 8
        ts_keys = np.frombuffer(data[off:off + 4 * tssz], ">u4") \
            .astype(np.int64)
        off += 4 * tssz
        off += 8  # compressedList count + last (we re-derive from deltas)
        (sz,) = _be32(data, off)
        off += 4
        if off + sz > len(data):
            raise ValueError("truncated HLL sparse payload (list)")
        deltas = _decode_varint_deltas(data[off:off + sz])
        keys = np.concatenate([ts_keys, np.cumsum(deltas)])
        if keys.shape[0]:
            idx, r = _decode_sparse_hashes_np(keys, p)
            acc = np.zeros(m, np.int64)
            np.maximum.at(acc, idx, r)
            regs = acc.astype(np.uint8)
        return p, regs
    (sz,) = _be32(data, 4)
    packed = np.frombuffer(data[8:8 + sz], np.uint8)
    if packed.shape[0] != m // 2:
        raise ValueError("HLL dense payload length mismatch")
    regs = np.empty(m, np.uint8)
    regs[0::2] = packed >> 4
    regs[1::2] = packed & 0x0F
    if b:
        regs = (regs.astype(np.int32) + b).astype(np.uint8)
    return p, regs


def _be32(data: bytes, off: int):
    return (int.from_bytes(data[off:off + 4], "big"),)


def deserialize(data: bytes):
    """Parse sketch wire bytes -> (precision, uint8 registers[2^p]).

    Accepts the reference's axiomhq MarshalBinary bytes (dense AND sparse
    forms) and this framework's legacy VHLL dump."""
    import numpy as np
    if data[:4] == MAGIC:
        precision = data[4]
        regs = np.frombuffer(data[5:], np.uint8)
        if regs.shape[0] != (1 << precision):
            raise ValueError("HLL payload length mismatch")
        return precision, regs
    if len(data) >= 8 and data[0] == 1 and 4 <= data[1] <= 18:
        return _deserialize_axiomhq(data)
    raise ValueError("unrecognized HLL payload")


@partial(jax.jit, static_argnames=("precision",))
def estimate(registers, *, precision: int = DEFAULT_PRECISION):
    """Cardinality estimate per key: f32[...] over registers [..., R].

    Classic HLL: alpha·m²/Σ2^-M_j, with linear counting m·ln(m/V) when the
    raw estimate is below 5/2·m and zero registers exist. The reference's
    vendored lib uses the LogLog-Beta variant; both sit inside the ~0.8%
    standard error at p=14, which is what the tests assert.
    """
    if registers.dtype != jnp.uint8:     # 6-bit packed i32 table
        # fused lane-extraction path: no dense u8 register staging —
        # value-exact vs the dense math below (tests/test_query.py), so
        # flush exports and query-tier reads agree on every backend
        return estimate_packed_rows(registers, precision=precision)
    m = num_registers(precision)
    regs = registers.astype(jnp.float32)
    inv = jnp.sum(jnp.exp2(-regs), axis=-1)
    raw = _alpha(m) * m * m / inv
    zeros = jnp.sum((registers == 0).astype(jnp.float32), axis=-1)
    lin = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_lin = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_lin, lin, raw)


@partial(jax.jit, static_argnames=("precision",))
def estimate_packed_rows(words, *, precision: int = DEFAULT_PRECISION):
    """Cardinality estimate straight from 6-bit packed i32 rows [..., W].

    The lane shift/mask table (the 16-register/3-word group layout of
    `unpack_registers`) feeds the harmonic estimator directly, so the
    whole thing is one fused device program over the packed words — no
    dense u8[..., 2^p] register array is ever staged as a separate pass,
    and nothing crosses to the host. The register values, the f32
    conversion and the reduction layout are identical to running
    `estimate` on the unpacked table, so the result is value-exact vs
    the dense path (tests/test_query.py pins this) — which is also what
    keeps query-tier cardinalities equal to what the flush would export.
    """
    m = num_registers(precision)
    w = packed_words(precision)
    assert words.shape[-1] == w
    g = _group16(words, 3)
    w0, w1, w2 = g[..., 0], g[..., 1], g[..., 2]
    lanes = [
        w0 & 0x3F, (w0 >> 6) & 0x3F, (w0 >> 12) & 0x3F, (w0 >> 18) & 0x3F,
        (w0 >> 24) & 0x3F,
        ((w0 >> 30) & 0x3) | ((w1 & 0xF) << 2),
        (w1 >> 4) & 0x3F, (w1 >> 10) & 0x3F, (w1 >> 16) & 0x3F,
        (w1 >> 22) & 0x3F,
        ((w1 >> 28) & 0xF) | ((w2 & 0x3) << 4),
        (w2 >> 2) & 0x3F, (w2 >> 8) & 0x3F, (w2 >> 14) & 0x3F,
        (w2 >> 20) & 0x3F, (w2 >> 26) & 0x3F,
    ]
    regs_i = jnp.stack(lanes, axis=-1).reshape(words.shape[:-1] + (m,))
    regs = regs_i.astype(jnp.float32)
    inv = jnp.sum(jnp.exp2(-regs), axis=-1)
    raw = _alpha(m) * m * m / inv
    zeros = jnp.sum((regs_i == 0).astype(jnp.float32), axis=-1)
    lin = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_lin = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_lin, lin, raw)
