"""Fused Pallas ingest kernel: the whole scatter chain in one pass.

`aggregation/step.py ingest_core` is a chain of separate XLA scatters —
counter add, gauge/status last-write-wins, HLL register max, digest
cell insert — each of which re-streams its state operand through HBM.
This module fuses them into ONE `pl.pallas_call` over VMEM-tiled state
blocks: every state leaf is read into VMEM once, takes all of its
batch's updates in place, and is written back once.

Shape of the kernel:

- The host-side prologue sorts each kind's batch lane by (slot, batch
  index) — reusing `_histo_plan` verbatim for the digest lane so cell
  assignment math is shared, not duplicated — maps invalid slots to a
  2^30 sentinel, and computes per-grid-step window offsets with one
  searchsorted per kind. The offsets ride as a scalar-prefetch operand
  (`pltpu.PrefetchScalarGridSpec`), so block index maps and loop bounds
  know them before the body runs.
- A 1-D grid walks each kind's blocks in slot order; a kind with fewer
  blocks than the grid clamps its index map (`min(g, blocks-1)`), which
  under Pallas revisit semantics keeps its last block resident in VMEM
  with no extra HBM traffic. Out blocks are copy-initialized from the
  aliased inputs on first visit only (`@pl.when(g < blocks)` — the
  first visit of block b is exactly grid step b), then mutated by
  sequential scalar read-modify-writes driven by
  `fori_loop(offs[k, g], offs[k, g + 1])`.
- Update order inside a window is ascending (slot, batch index), so per
  slot the adds/sets land in batch order — exactly the order XLA
  applies duplicate scatter updates — which is what makes the kernel
  BYTE-identical to the scatter chain on every state leaf
  (tests/test_pallas_ingest.py pins this in interpret mode).
- HLL registers update directly in the 6-bit packed words
  (ops/hll.py §packed): a register's field is read with a
  shift/mask, maxed with rho, and written back; a field straddling a
  word boundary (in-word bit 28 or 30) patches the second word under
  `@pl.when(straddle)`. Since 2^p % 16 == 0 a straddle never occurs at
  a row's final word, so the second word always exists.

Gating mirrors ops/pallas_digest.py: `enabled()` probes the backend in
a bounded subprocess (any Mosaic lowering gap → XLA fallback, never a
crash), `VENEUR_TPU_PALLAS_INGEST=1/0` force-overrides, and the
`pallas_ingest_enabled` config key feeds `set_enabled` at server
construction. On CPU the kernel runs in interpret mode (traced JAX
ops) — correct everywhere, used by the parity suite; the production
CPU path stays the XLA chain.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from veneur_tpu.aggregation.state import DeviceState, TableSpec

log = logging.getLogger(__name__)

_BIG = 1 << 30   # sentinel slot for invalid rows: beyond every window


def _tiles(spec: TableSpec):
    """Per-kind VMEM tile rows (counter, gauge, status, set, histo).
    Budgeted so in+out blocks of every kind fit ~6MB total at the
    default spec — half a core's VMEM, leaving room for the streams."""
    tc = min(spec.counter_capacity, 1 << 15)
    tg = min(spec.gauge_capacity, 1 << 15)
    tst = min(spec.status_capacity, 1 << 15)
    ts = max(1, min(spec.set_capacity, (1 << 18) // spec.hll_words))
    th = max(1, min(spec.histo_capacity, (1 << 17) // spec.total_cells))
    return tc, tg, tst, ts, th


def _layout(spec: TableSpec):
    tiles = _tiles(spec)
    caps = (spec.counter_capacity, spec.gauge_capacity,
            spec.status_capacity, spec.set_capacity, spec.histo_capacity)
    nblocks = tuple(-(-c // t) for c, t in zip(caps, tiles))
    return tiles, caps, nblocks, max(nblocks)


def _pad1(a):
    """A zero-length lane still needs a nonempty VMEM block; one sentinel
    row (slot == _BIG lands outside every window) keeps the BlockSpec
    legal without a second compiled variant."""
    if a.shape[0] > 0:
        return a
    return jnp.zeros((1,) + a.shape[1:], a.dtype)


def _stream(slot, cap, *vals, extra_valid=None):
    """Sort one lane by (slot, batch index); invalid rows — negative or
    past-capacity slots — keep their relative order at the tail under the
    _BIG sentinel, outside every window. (The XLA chain's mode="drop"
    scatters WRAP negative slots, NumPy-style; production never emits
    them — padding rows carry slot == capacity — so dropping here is the
    saner twin behavior, same call as hll.merge_rows_packed.)"""
    valid = (slot >= 0) & (slot < cap)
    if extra_valid is not None:
        valid = valid & extra_valid
    skey = jnp.where(valid, slot, _BIG)
    idx = jnp.arange(slot.shape[0], dtype=jnp.int32)
    order = jnp.lexsort((idx, skey))
    return (_pad1(skey[order].astype(jnp.int32)),
            tuple(_pad1(v[order]) for v in vals))


def _offsets(skeys, tiles, g_total):
    """i32[5, G+1] window offsets: row k, step g covers sorted positions
    [offs[k, g], offs[k, g+1]) — the slots in [g*tile_k, (g+1)*tile_k).
    Steps past a kind's last block get empty windows (every valid slot
    is below blocks_k * tile_k); sentinel rows sit past offs[k, G]."""
    rows = []
    for sk, t in zip(skeys, tiles):
        bounds = jnp.arange(g_total + 1, dtype=jnp.int32) * t
        rows.append(jnp.searchsorted(sk, bounds, side="left")
                    .astype(jnp.int32))
    return jnp.stack(rows)


def fused_ingest_core(state: DeviceState, batch, *, spec: TableSpec,
                      interpret: bool = False) -> DeviceState:
    """Drop-in replacement for ingest_core's scatter chain (everything
    except the optional histo_stat_* import lanes and the two-float
    fold, which stay in XLA around the kernel). Pure; safe under jit
    and donation — state leaves alias the kernel outputs."""
    from veneur_tpu.aggregation.step import _histo_plan

    tiles, _caps, nblocks, g_total = _layout(spec)
    tc, tg, tst, ts, th = tiles
    ncb, ngb, nstb, nsb, nhb = nblocks
    w_words = spec.hll_words
    cells = spec.total_cells

    c_sk, (c_inc,) = _stream(batch.counter_slot, spec.counter_capacity,
                             batch.counter_inc)
    g_sk, (g_val,) = _stream(batch.gauge_slot, spec.gauge_capacity,
                             batch.gauge_val)
    st_sk, (st_val,) = _stream(batch.status_slot, spec.status_capacity,
                               batch.status_val)
    # the dense scatter drops out-of-range register indices too (2-D
    # scatter, mode="drop") — mirror that in the stream validity
    reg_ok = (batch.set_reg >= 0) & (batch.set_reg < spec.registers)
    s_sk, (s_reg, s_rho) = _stream(
        batch.set_slot, spec.set_capacity, batch.set_reg,
        batch.set_rho.astype(jnp.int32), extra_valid=reg_ok)
    hs, h_cell, h_v, h_w, h_tadd = _histo_plan(
        state, batch.histo_slot, batch.histo_val, batch.histo_wt, spec)
    # _histo_plan already sorted by (slot, value) with invalid rows at
    # slot == histo_capacity; only the sentinel remap is needed, and the
    # kernel consumes the EXACT arrays the scatter chain would.
    h_sk = _pad1(jnp.where(hs < spec.histo_capacity, hs,
                           jnp.int32(_BIG)).astype(jnp.int32))
    h_cell, h_v, h_w, h_tadd = (_pad1(h_cell), _pad1(h_v),
                                _pad1(h_w), _pad1(h_tadd))
    h_wv = h_w * h_v
    h_rcp = jnp.where(h_w > 0, h_w / h_v, 0.0)

    offs = _offsets([c_sk, g_sk, st_sk, s_sk, h_sk], tiles, g_total)

    def kernel(offs_ref,
               counter_in, gauge_in, gstamp_in, status_in, ststamp_in,
               hll_in, hw_in, hwm_in, htn_in, hmin_in, hmax_in,
               hcnt_in, hsum_in, hrcp_in,
               c_slot_s, c_inc_s, g_slot_s, g_val_s, st_slot_s, st_val_s,
               s_slot_s, s_reg_s, s_rho_s,
               h_slot_s, h_cell_s, h_v_s, h_w_s, h_wv_s, h_rcp_s, h_tadd_s,
               counter_out, gauge_out, gstamp_out, status_out, ststamp_out,
               hll_out, hw_out, hwm_out, htn_out, hmin_out, hmax_out,
               hcnt_out, hsum_out, hrcp_out):
        g = pl.program_id(0)

        # copy-initialize out blocks from the aliased inputs on FIRST
        # visit only: the clamped index maps revisit each kind's last
        # block, and re-copying would erase the resident RMW results
        for dst, src, nb in ((counter_out, counter_in, ncb),
                             (gauge_out, gauge_in, ngb),
                             (gstamp_out, gstamp_in, ngb),
                             (status_out, status_in, nstb),
                             (ststamp_out, ststamp_in, nstb),
                             (hll_out, hll_in, nsb),
                             (hw_out, hw_in, nhb),
                             (hwm_out, hwm_in, nhb),
                             (htn_out, htn_in, nhb),
                             (hmin_out, hmin_in, nhb),
                             (hmax_out, hmax_in, nhb),
                             (hcnt_out, hcnt_in, nhb),
                             (hsum_out, hsum_in, nhb),
                             (hrcp_out, hrcp_in, nhb)):
            @pl.when(g < nb)
            def _(dst=dst, src=src):
                dst[...] = src[...]

        cbase = jnp.minimum(g, ncb - 1) * tc

        def c_body(i, _):
            counter_out[c_slot_s[i] - cbase] += c_inc_s[i]
            return 0

        jax.lax.fori_loop(offs_ref[0, g], offs_ref[0, g + 1], c_body, 0)

        gbase = jnp.minimum(g, ngb - 1) * tg

        def g_body(i, _):
            l = g_slot_s[i] - gbase
            gauge_out[l] = g_val_s[i]
            gstamp_out[l] = jnp.uint8(1)
            return 0

        jax.lax.fori_loop(offs_ref[1, g], offs_ref[1, g + 1], g_body, 0)

        stbase = jnp.minimum(g, nstb - 1) * tst

        def st_body(i, _):
            l = st_slot_s[i] - stbase
            status_out[l] = st_val_s[i]
            ststamp_out[l] = jnp.uint8(1)
            return 0

        jax.lax.fori_loop(offs_ref[2, g], offs_ref[2, g + 1], st_body, 0)

        sbase = jnp.minimum(g, nsb - 1) * ts

        def s_body(i, _):
            l = s_slot_s[i] - sbase
            bit = 6 * s_reg_s[i]
            w0 = bit >> 5
            sh = bit & 31
            straddle = sh > 26
            nlo = jnp.where(straddle, 32 - sh, 6)
            nhi = 6 - nlo                     # 0 when the field fits
            mask_lo = (1 << nlo) - 1
            lo = hll_out[l, w0]
            w1 = jnp.where(straddle, w0 + 1, w0)  # guard: no OOB read
            hi = hll_out[l, w1]
            cur = ((lo >> sh) & mask_lo) | ((hi & ((1 << nhi) - 1)) << nlo)
            new = jnp.maximum(cur, s_rho_s[i])
            hll_out[l, w0] = ((lo & ~(mask_lo << sh))
                              | ((new & mask_lo) << sh))

            @pl.when(straddle)
            def _():
                hll_out[l, w1] = (hi & ~((1 << nhi) - 1)) | (new >> nlo)
            return 0

        jax.lax.fori_loop(offs_ref[3, g], offs_ref[3, g + 1], s_body, 0)

        hbase = jnp.minimum(g, nhb - 1) * th

        def h_body(i, _):
            l = h_slot_s[i] - hbase
            cell = h_cell_s[i]
            v = h_v_s[i]
            w = h_w_s[i]
            wv = h_wv_s[i]
            hw_out[l, cell] += w
            hwm_out[l, cell] += wv
            htn_out[l] += h_tadd_s[i]
            hmin_out[l] = jnp.minimum(hmin_out[l],
                                      jnp.where(w > 0, v, jnp.inf))
            hmax_out[l] = jnp.maximum(hmax_out[l],
                                      jnp.where(w > 0, v, -jnp.inf))
            hcnt_out[l] += w
            hsum_out[l] += wv
            hrcp_out[l] += h_rcp_s[i]
            return 0

        jax.lax.fori_loop(offs_ref[4, g], offs_ref[4, g + 1], h_body, 0)

    state_ins = (state.counter_acc, state.gauge, state.gauge_stamp,
                 state.status, state.status_stamp, state.hll,
                 state.h_w, state.h_wm, state.h_temp_n,
                 state.h_min, state.h_max,
                 state.h_count_acc, state.h_sum_acc, state.h_recip_acc)
    streams = (c_sk, c_inc, g_sk, g_val, st_sk, st_val,
               s_sk, s_reg, s_rho,
               h_sk, h_cell, h_v, h_w, h_wv, h_rcp, h_tadd)

    def spec1(tile, nb):
        return pl.BlockSpec((tile,), lambda g, o, nb=nb: (jnp.minimum(g, nb - 1),))

    def spec2(tile, ncols, nb):
        return pl.BlockSpec((tile, ncols),
                            lambda g, o, nb=nb: (jnp.minimum(g, nb - 1), 0))

    def whole(n):
        return pl.BlockSpec((n,), lambda g, o: (0,))

    state_specs = [
        spec1(tc, ncb), spec1(tg, ngb), spec1(tg, ngb),
        spec1(tst, nstb), spec1(tst, nstb),
        spec2(ts, w_words, nsb),
        spec2(th, cells, nhb), spec2(th, cells, nhb),
        spec1(th, nhb), spec1(th, nhb), spec1(th, nhb),
        spec1(th, nhb), spec1(th, nhb), spec1(th, nhb),
    ]
    stream_specs = [whole(a.shape[0]) for a in streams]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g_total,),
        in_specs=state_specs + stream_specs,
        out_specs=state_specs,
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(a.shape, a.dtype)
                   for a in state_ins],
        # operand 0 is the scalar-prefetch offsets; state input i is
        # operand i+1, aliased in place onto output i
        input_output_aliases={i + 1: i for i in range(len(state_ins))},
        interpret=interpret,
    )(offs, *state_ins, *streams)
    return state._replace(
        counter_acc=outs[0], gauge=outs[1], gauge_stamp=outs[2],
        status=outs[3], status_stamp=outs[4], hll=outs[5],
        h_w=outs[6], h_wm=outs[7], h_temp_n=outs[8],
        h_min=outs[9], h_max=outs[10],
        h_count_acc=outs[11], h_sum_acc=outs[12], h_recip_acc=outs[13])


# -- gating ------------------------------------------------------------------

_PROBE_RESULT = None
_OVERRIDE = None


def set_enabled(value) -> None:
    """Config-level override wired from `pallas_ingest_enabled` at server
    construction: False forces the XLA chain, True forces the kernel
    (interpret mode on CPU), None restores probe gating."""
    global _OVERRIDE
    _OVERRIDE = value


def interpret_mode() -> bool:
    """Run the kernel as traced JAX ops (bit-identical semantics, no
    Mosaic) — the portable mode tier-1 parity uses on CPU."""
    return jax.default_backend() == "cpu"


def active() -> bool:
    """Should ingest_core take the fused path right now?"""
    if _OVERRIDE is not None:
        return bool(_OVERRIDE)
    return enabled()


def enabled() -> bool:
    """Probe-gated availability, mirroring pallas_digest.enabled():
    VENEUR_TPU_PALLAS_INGEST=1/0 forces; CPU backend → False (the XLA
    chain is faster than interpret mode); otherwise a bounded-subprocess
    parity probe decides once per process."""
    env = os.environ.get("VENEUR_TPU_PALLAS_INGEST", "")
    if env == "1":
        return True
    if env == "0":
        return False
    if jax.default_backend() == "cpu":
        return False
    global _PROBE_RESULT
    if _PROBE_RESULT is None:
        try:
            _PROBE_RESULT = _run_probe_bounded()
        except Exception as exc:  # noqa: BLE001 - any probe failure = no
            log.warning("pallas ingest probe failed; using XLA chain: %s",
                        exc)
            _PROBE_RESULT = False
        if not _PROBE_RESULT:
            log.warning("pallas ingest kernel unavailable on %s; "
                        "falling back to the XLA scatter chain",
                        jax.default_backend())
    return _PROBE_RESULT


def _probe_spec() -> TableSpec:
    return TableSpec(counter_capacity=64, gauge_capacity=64,
                     status_capacity=32, set_capacity=8,
                     histo_capacity=32, hll_precision=6, temp_cells=16)


def _probe_batch(spec: TableSpec):
    import numpy as np
    from veneur_tpu.aggregation.step import Batch
    rng = np.random.default_rng(7)
    n = 32

    def slots(cap):
        return jnp.asarray(rng.integers(0, cap + 2, n).astype(np.int32))

    return Batch(
        counter_slot=slots(spec.counter_capacity),
        counter_inc=jnp.asarray(rng.normal(size=n).astype(np.float32)),
        gauge_slot=slots(spec.gauge_capacity),
        gauge_val=jnp.asarray(rng.normal(size=n).astype(np.float32)),
        status_slot=slots(spec.status_capacity),
        status_val=jnp.asarray(rng.normal(size=n).astype(np.float32)),
        set_slot=slots(spec.set_capacity),
        set_reg=jnp.asarray(
            rng.integers(0, spec.registers, n).astype(np.int32)),
        set_rho=jnp.asarray(rng.integers(0, 50, n).astype(np.uint8)),
        histo_slot=slots(spec.histo_capacity),
        histo_val=jnp.asarray(
            rng.normal(size=n).astype(np.float32) + 2.0),
        histo_wt=jnp.asarray(
            rng.uniform(0.5, 2.0, n).astype(np.float32)),
    )


def _probe() -> bool:
    """Compiled fused kernel vs the XLA chain on the live backend —
    exact equality on every state leaf, in the production calling
    context (inside jit)."""
    import numpy as np
    from functools import partial
    from veneur_tpu.aggregation import step
    from veneur_tpu.aggregation.state import empty_state

    spec = _probe_spec()
    batch = _probe_batch(spec)
    ref = jax.jit(partial(step.ingest_core, spec=spec,
                          allow_pallas=False))(empty_state(spec), batch)

    def fused_core(state, batch):
        state = fused_ingest_core(state, batch, spec=spec, interpret=False)
        return step._fold_core(state)

    fused = jax.jit(fused_core)(empty_state(spec), batch)
    for a, b in zip(ref, fused):
        if not np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True):
            return False
    return True


def _run_probe_bounded(budget_s: float = 60.0) -> bool:
    """Run _probe in a subprocess with a hard wall-clock budget: a Mosaic
    lowering bug or a wedged backend must degrade to the XLA chain, not
    hang or kill the server (same containment as pallas_digest)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    code = ("import sys; sys.path.insert(0, %r); "
            "from veneur_tpu.ops.pallas_ingest import _probe; "
            "print('PALLAS_INGEST_OK' if _probe() else 'PALLAS_INGEST_NO')"
            % root)
    try:
        res = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=budget_s)
    except subprocess.TimeoutExpired:
        log.warning("pallas ingest probe exceeded %.0fs budget", budget_s)
        return False
    return "PALLAS_INGEST_OK" in res.stdout
