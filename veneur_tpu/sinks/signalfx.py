"""SignalFx metric sink (reference sinks/signalfx/signalfx.go).

Datapoints posted as JSON to `{endpoint}/v2/datapoint` with an X-SF-Token
header; counters as cumulative counters, everything else as gauges. The
reference's per-tag API-token fan-out (vary_key_by + per-tag token map,
signalfx.go:240-344) selects a client per metric by the value of one tag.
No sfxclient dependency — urllib like the datadog sink.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import Dict, List

from veneur_tpu.samplers.intermetric import COUNTER, InterMetric
from veneur_tpu.sinks.base import MetricSink, filter_acceptable

log = logging.getLogger("veneur_tpu.sinks.signalfx")


class SignalFxMetricSink(MetricSink):
    name = "signalfx"

    def __init__(self, api_key: str, endpoint: str, hostname: str,
                 hostname_tag: str = "host",
                 vary_key_by: str = "",
                 per_tag_api_keys: Dict[str, str] = None,
                 flush_max_per_body: int = 5000,
                 metric_name_prefix_drops: List[str] = (),
                 metric_tag_prefix_drops: List[str] = (),
                 tags: List[str] = ()):
        self.api_key = api_key
        self.endpoint = endpoint.rstrip("/")
        self.hostname = hostname
        self.hostname_tag = hostname_tag
        self.vary_key_by = vary_key_by
        self.per_tag_api_keys = dict(per_tag_api_keys or {})
        self.flush_max_per_body = flush_max_per_body
        self.prefix_drops = list(metric_name_prefix_drops)
        self.tag_prefix_drops = list(metric_tag_prefix_drops)
        self.common_tags = list(tags)

    def _datapoint(self, m: InterMetric):
        dims = {self.hostname_tag: m.hostname or self.hostname}
        for t in self.strip_excluded(m.tags) + self.common_tags:
            if any(t.startswith(p) for p in self.tag_prefix_drops):
                continue
            k, _, v = t.partition(":")
            dims[k] = v
        return {"metric": m.name, "value": m.value,
                "timestamp": int(m.timestamp * 1000), "dimensions": dims}

    def _token_for(self, m: InterMetric) -> str:
        """vary-by token selection (signalfx.go client fan-out)."""
        if self.vary_key_by:
            prefix = self.vary_key_by + ":"
            for t in m.tags:
                if t.startswith(prefix):
                    return self.per_tag_api_keys.get(t[len(prefix):],
                                                     self.api_key)
        return self.api_key

    def flush(self, metrics):
        metrics = filter_acceptable(metrics, self.name)
        by_token: Dict[str, Dict[str, list]] = {}
        for m in metrics:
            if any(m.name.startswith(p) for p in self.prefix_drops):
                continue
            kind = "counter" if m.type == COUNTER else "gauge"
            body = by_token.setdefault(self._token_for(m),
                                       {"counter": [], "gauge": []})
            body[kind].append(self._datapoint(m))
        for token, body in by_token.items():
            # chunk across BOTH kinds so one POST never exceeds
            # flush_max_per_body total points
            points = ([("counter", p) for p in body["counter"]]
                      + [("gauge", p) for p in body["gauge"]])
            for i in range(0, len(points), self.flush_max_per_body):
                chunk = {"counter": [], "gauge": []}
                for kind, p in points[i:i + self.flush_max_per_body]:
                    chunk[kind].append(p)
                self._post(token, chunk)

    def _post(self, token, body):
        req = urllib.request.Request(
            f"{self.endpoint}/v2/datapoint",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json",
                     "X-SF-Token": token})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
        except Exception as e:
            log.error("signalfx flush failed: %s", e)
