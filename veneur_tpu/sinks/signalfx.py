"""SignalFx metric sink (reference sinks/signalfx/signalfx.go).

Datapoints posted as JSON to `{endpoint}/v2/datapoint` with an X-SF-Token
header; counters as cumulative counters, everything else as gauges. The
reference's per-tag API-token fan-out (vary_key_by + per-tag token map,
signalfx.go:240-344) selects a client per metric by the value of one tag.
No sfxclient dependency — urllib like the datadog sink.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import Dict, List

from veneur_tpu.samplers.intermetric import (
    COUNTER, SINK_ONLY_TAG_PREFIX, InterMetric)
from veneur_tpu.sinks.base import MetricSink, filter_acceptable

# the dimension KEY the routing tag produces ("veneursinkonly:x" and the
# bare "veneursinkonly" both partition to this)
_SINK_ONLY_KEY = SINK_ONLY_TAG_PREFIX.rstrip(":")

log = logging.getLogger("veneur_tpu.sinks.signalfx")


class SignalFxMetricSink(MetricSink):
    name = "signalfx"

    def __init__(self, api_key: str, endpoint: str, hostname: str,
                 hostname_tag: str = "host",
                 vary_key_by: str = "",
                 per_tag_api_keys: Dict[str, str] = None,
                 flush_max_per_body: int = 5000,
                 metric_name_prefix_drops: List[str] = (),
                 metric_tag_prefix_drops: List[str] = (),
                 tags: List[str] = ()):
        self.api_key = api_key
        self.endpoint = endpoint.rstrip("/")
        self.hostname = hostname
        self.hostname_tag = hostname_tag
        self.vary_key_by = vary_key_by
        self.per_tag_api_keys = dict(per_tag_api_keys or {})
        self.flush_max_per_body = flush_max_per_body
        self.prefix_drops = list(metric_name_prefix_drops)
        self.tag_prefix_drops = list(metric_tag_prefix_drops)
        self.common_tags = list(tags)

    def _datapoint_from(self, name, ts, value, tags, host):
        """The ONE datapoint serialization both flush paths share."""
        dims = {self.hostname_tag: host or self.hostname}
        for t in self.strip_excluded(tags) + self.common_tags:
            if any(t.startswith(p) for p in self.tag_prefix_drops):
                continue
            k, _, v = t.partition(":")
            if k == _SINK_ONLY_KEY:
                continue  # routing tag, never a dimension (signalfx.go:465
                #           deletes exactly this dimension key)
            dims[k] = v
        return {"metric": name, "value": value,
                "timestamp": int(ts * 1000), "dimensions": dims}

    def _datapoint(self, m: InterMetric):
        return self._datapoint_from(m.name, m.timestamp, m.value, m.tags,
                                    m.hostname)

    def _token_for(self, tags) -> str:
        """vary-by token selection (signalfx.go client fan-out)."""
        if self.vary_key_by:
            prefix = self.vary_key_by + ":"
            for t in tags:
                if t.startswith(prefix):
                    return self.per_tag_api_keys.get(t[len(prefix):],
                                                     self.api_key)
        return self.api_key

    def flush(self, metrics):
        metrics = filter_acceptable(metrics, self.name)
        self._flush_rows(
            (m.name, m.timestamp, m.value, m.type, m.tags, m.hostname)
            for m in metrics)

    def flush_frame(self, frame):
        """Columnar flush via frame.rows() — identical emission rules,
        no InterMetric materialization (see flusher.MetricFrame)."""
        ts = frame.timestamp
        self._flush_rows(
            (name, ts, value, mtype, tags, host)
            for name, value, mtype, _msg, tags, sinks, host
            in frame.rows()
            if sinks is None or self.name in sinks)

    def _flush_rows(self, rows):
        by_token: Dict[str, Dict[str, list]] = {}
        for name, ts, value, mtype, tags, host in rows:
            if any(name.startswith(p) for p in self.prefix_drops):
                continue
            kind = "counter" if mtype == COUNTER else "gauge"
            body = by_token.setdefault(self._token_for(tags),
                                       {"counter": [], "gauge": []})
            body[kind].append(self._datapoint_from(name, ts, value, tags,
                                                   host))
        for token, body in by_token.items():
            # chunk across BOTH kinds so one POST never exceeds
            # flush_max_per_body total points
            points = ([("counter", p) for p in body["counter"]]
                      + [("gauge", p) for p in body["gauge"]])
            for i in range(0, len(points), self.flush_max_per_body):
                chunk = {"counter": [], "gauge": []}
                for kind, p in points[i:i + self.flush_max_per_body]:
                    chunk[kind].append(p)
                self._post(token, chunk)

    def _post(self, token, body):
        req = urllib.request.Request(
            f"{self.endpoint}/v2/datapoint",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json",
                     "X-SF-Token": token})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
        except Exception as e:
            log.error("signalfx flush failed: %s", e)
