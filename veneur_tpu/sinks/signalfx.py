"""SignalFx metric sink (reference sinks/signalfx/signalfx.go).

Datapoints posted as JSON to `{endpoint}/v2/datapoint` with an X-SF-Token
header; counters as cumulative counters, everything else as gauges. The
reference's per-tag API-token fan-out (vary_key_by + per-tag token map,
signalfx.go:240-344) selects a client per metric by the value of one tag;
with dynamic fetch enabled, the tag→token map is re-fetched periodically
from the SignalFx tokens API (signalfx.go:250-344). DogStatsD events are
posted to the events API (signalfx.go:501 FlushOtherSamples →
reportEvent). No sfxclient dependency — urllib like the datadog sink.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
import urllib.request
from typing import Dict, List

from veneur_tpu.samplers.intermetric import (
    COUNTER, SINK_ONLY_TAG_PREFIX, InterMetric)
from veneur_tpu.sinks.base import (MetricSink, ResilientSink,
                                   filter_acceptable)

# the dimension KEY the routing tag produces ("veneursinkonly:x" and the
# bare "veneursinkonly" both partition to this)
_SINK_ONLY_KEY = SINK_ONLY_TAG_PREFIX.rstrip(":")

# reference signalfx.go:27-28
EVENT_NAME_MAX_LENGTH = 256
EVENT_DESCRIPTION_MAX_LENGTH = 256
# tokens-API pagination (reference signalfx.go:273-277)
_TOKEN_PAGE_LIMIT = 200
# pagination backstop: a server that keeps returning full pages (or
# ignores offset) must not spin the token fetch forever; 500 pages =
# 100k tokens, far past any real org
_TOKEN_MAX_PAGES = 500

log = logging.getLogger("veneur_tpu.sinks.signalfx")


class SignalFxMetricSink(ResilientSink, MetricSink):
    name = "signalfx"

    def __init__(self, api_key: str, endpoint: str, hostname: str,
                 hostname_tag: str = "host",
                 vary_key_by: str = "",
                 per_tag_api_keys: Dict[str, str] = None,
                 flush_max_per_body: int = 5000,
                 metric_name_prefix_drops: List[str] = (),
                 metric_tag_prefix_drops: List[str] = (),
                 tags: List[str] = (),
                 dynamic_per_tag_tokens_enable: bool = False,
                 dynamic_per_tag_tokens_refresh_s: float = 300.0,
                 api_endpoint: str = "https://api.signalfx.com"):
        self.api_key = api_key
        self.endpoint = endpoint.rstrip("/")
        self.hostname = hostname
        self.hostname_tag = hostname_tag
        self.vary_key_by = vary_key_by
        self.per_tag_api_keys = dict(per_tag_api_keys or {})
        self.flush_max_per_body = flush_max_per_body
        self.prefix_drops = list(metric_name_prefix_drops)
        self.tag_prefix_drops = list(metric_tag_prefix_drops)
        self.common_tags = list(tags)
        self.dynamic_per_tag_tokens_enable = dynamic_per_tag_tokens_enable
        # floor of 1s: a configured "0s" must degrade to a fast refresh,
        # not an unthrottled busy loop against the tokens API
        self.dynamic_per_tag_tokens_refresh_s = max(
            1.0, dynamic_per_tag_tokens_refresh_s)
        self.api_endpoint = api_endpoint.rstrip("/")
        self._refresh_stop = threading.Event()
        self._refresher = None

    def start(self):
        """Arm the periodic tag→token refresher (reference
        signalfx.go:250 clientByTagUpdater goroutine)."""
        if not self.dynamic_per_tag_tokens_enable:
            return
        self._refresher = threading.Thread(
            target=self._refresh_loop, daemon=True,
            name="signalfx-token-refresh")
        self._refresher.start()

    def stop(self):
        self._refresh_stop.set()

    def _refresh_loop(self):
        while not self._refresh_stop.wait(
                self.dynamic_per_tag_tokens_refresh_s):
            self.refresh_tokens_once()

    def refresh_tokens_once(self) -> bool:
        """One fetch of the full tag→token map from the SignalFx tokens
        API; merge on success, keep-last-good on any failure (reference
        signalfx.go:256-269: a failed fetch logs a warning and leaves
        the existing per-tag clients untouched)."""
        try:
            tokens = self._fetch_api_keys()
        except Exception as e:
            log.warning("failed to fetch new tokens from SignalFx: %s", e)
            return False
        # merge (not replace): the reference only overwrites/creates
        # clients for fetched names, never deletes existing ones.
        # Copy-on-rebind keeps _token_for lock-free on the per-datapoint
        # flush hot path (the GIL makes the rebind atomic, the same read
        # semantics as the reference's RWMutex).
        merged = dict(self.per_tag_api_keys)
        merged.update(tokens)
        self.per_tag_api_keys = merged
        log.debug("fetched %d signalfx tokens", len(tokens))
        return True

    def _fetch_api_keys(self) -> Dict[str, str]:
        """Paginated GET {api_endpoint}/v2/token until a SHORT page
        (reference signalfx.go:321-344 fetchAPIKeys): each result row
        contributes name → secret. A page under the requested limit is
        the last one — stopping only on an EMPTY page pays one wasted
        round-trip per refresh and spins forever against a server that
        ignores offset; _TOKEN_MAX_PAGES backstops even that."""
        out: Dict[str, str] = {}
        offset = 0
        for _page in range(_TOKEN_MAX_PAGES):
            q = urllib.parse.urlencode({
                "limit": _TOKEN_PAGE_LIMIT, "name": "", "offset": offset})
            req = urllib.request.Request(
                f"{self.api_endpoint}/v2/token?{q}",
                headers={"Content-Type": "application/json",
                         "X-SF-Token": self.api_key})
            with urllib.request.urlopen(req, timeout=10) as resp:
                if resp.status != 200:
                    raise RuntimeError(
                        f"signalfx api returned {resp.status}")
                body = json.loads(resp.read())
            results = body.get("results")
            if not isinstance(results, list):
                raise ValueError(
                    "unknown results structure returned from signalfx api")
            count = 0
            for row in results:
                if not isinstance(row, dict) or \
                        not isinstance(row.get("name"), str) or \
                        not isinstance(row.get("secret"), str):
                    raise ValueError(
                        "unknown result structure returned from "
                        "signalfx api")
                out[row["name"]] = row["secret"]
                count += 1
            if count < _TOKEN_PAGE_LIMIT:
                return out
            offset += _TOKEN_PAGE_LIMIT
        log.warning("signalfx token fetch stopped at the %d-page cap "
                    "with every page full; token list may be truncated",
                    _TOKEN_MAX_PAGES)
        return out

    def _datapoint_from(self, name, ts, value, tags, host):
        """The ONE datapoint serialization both flush paths share."""
        dims = {self.hostname_tag: host or self.hostname}
        for t in self.strip_excluded(tags) + self.common_tags:
            if any(t.startswith(p) for p in self.tag_prefix_drops):
                continue
            k, _, v = t.partition(":")
            if k == _SINK_ONLY_KEY:
                continue  # routing tag, never a dimension (signalfx.go:465
                #           deletes exactly this dimension key)
            dims[k] = v
        return {"metric": name, "value": value,
                "timestamp": int(ts * 1000), "dimensions": dims}

    def _datapoint(self, m: InterMetric):
        return self._datapoint_from(m.name, m.timestamp, m.value, m.tags,
                                    m.hostname)

    def _token_for(self, tags) -> str:
        """vary-by token selection (signalfx.go client fan-out)."""
        if self.vary_key_by:
            prefix = self.vary_key_by + ":"
            for t in tags:
                if t.startswith(prefix):
                    return self.per_tag_api_keys.get(t[len(prefix):],
                                                     self.api_key)
        return self.api_key

    def flush_other_samples(self, samples):
        """DogStatsD events → SignalFx events API (reference
        signalfx.go:501 FlushOtherSamples: only samples carrying the
        vdogstatsd_ev conduit tag are events; everything else is
        ignored)."""
        events = []
        for s in samples:
            tags = dict(s.tags) if s.tags else {}
            if "vdogstatsd_ev" not in tags:
                continue
            events.append(self._event_body(s, tags))
        if events:
            self._post_events(events)

    def _event_body(self, s, tags):
        """One SignalFx event (reference signalfx.go:546-591
        reportEvent): common dims + hostname + sample tags (conduit key
        dropped, excluded tags stripped), name/description truncated at
        256, Datadog markdown fences chopped out of the message."""
        dims = {}
        for t in self.common_tags:
            k, _, v = t.partition(":")
            dims[k] = v
        dims[self.hostname_tag] = self.hostname
        for k, v in tags.items():
            if k != "vdogstatsd_ev":
                dims[k] = v
        for e in getattr(self, "excluded_tags", ()):
            dims.pop(e, None)
        name = (s.name or "")[:EVENT_NAME_MAX_LENGTH]
        # reference order (signalfx.go:563-576): truncate FIRST, then
        # chop the Datadog markdown fences (first occurrence each), then
        # trim — a >256-char message loses its trailing fence to the
        # truncation before the replace could match it
        message = (s.message or "")[:EVENT_DESCRIPTION_MAX_LENGTH]
        message = message.replace("%%% \n", "", 1)
        message = message.replace("\n %%%", "", 1)
        message = message.strip()
        return {
            "eventType": name,
            "category": "USERDEFINED",
            "dimensions": dims,
            "properties": {"description": message},
            "timestamp": int(s.timestamp) * 1000,
        }

    def _post_events(self, events):
        req = urllib.request.Request(
            f"{self.endpoint}/v2/event",
            data=json.dumps(events).encode(), method="POST",
            headers={"Content-Type": "application/json",
                     "X-SF-Token": self.api_key})

        def once():
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()

        try:
            self.resilient_post(once, what="event")
        except Exception as e:
            log.error("signalfx event flush failed: %s", e)

    def flush(self, metrics):
        metrics = filter_acceptable(metrics, self.name)
        self._flush_rows(
            (m.name, m.timestamp, m.value, m.type, m.tags, m.hostname)
            for m in metrics)

    def flush_frame(self, frame):
        """Columnar flush via frame.rows() — identical emission rules,
        no InterMetric materialization (see flusher.MetricFrame)."""
        ts = frame.timestamp
        self._flush_rows(
            (name, ts, value, mtype, tags, host)
            for name, value, mtype, _msg, tags, sinks, host
            in frame.rows()
            if sinks is None or self.name in sinks)

    def _flush_rows(self, rows):
        by_token: Dict[str, Dict[str, list]] = {}
        for name, ts, value, mtype, tags, host in rows:
            if any(name.startswith(p) for p in self.prefix_drops):
                continue
            kind = "counter" if mtype == COUNTER else "gauge"
            body = by_token.setdefault(self._token_for(tags),
                                       {"counter": [], "gauge": []})
            body[kind].append(self._datapoint_from(name, ts, value, tags,
                                                   host))
        for token, body in by_token.items():
            # chunk across BOTH kinds so one POST never exceeds
            # flush_max_per_body total points
            points = ([("counter", p) for p in body["counter"]]
                      + [("gauge", p) for p in body["gauge"]])
            for i in range(0, len(points), self.flush_max_per_body):
                chunk = {"counter": [], "gauge": []}
                for kind, p in points[i:i + self.flush_max_per_body]:
                    chunk[kind].append(p)
                self._post(token, chunk)

    def _post(self, token, body):
        req = urllib.request.Request(
            f"{self.endpoint}/v2/datapoint",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json",
                     "X-SF-Token": token})

        def once():
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()

        try:
            self.resilient_post(once, what="datapoint")
        except Exception as e:
            log.error("signalfx flush failed: %s", e)
