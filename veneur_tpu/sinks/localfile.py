"""Flush-to-file plugin (reference plugins/localfile/localfile.go: TSV
append of every final InterMetric batch) and the CSV encoding shared with
the S3 plugin (reference plugins/s3/csv.go EncodeInterMetricCSV) —
byte-compatible with the reference's rows so existing Redshift/S3
loaders keep working."""

from __future__ import annotations

import csv
import gzip
import io
import logging
import time

import numpy as np

from veneur_tpu.samplers.intermetric import COUNTER, GAUGE, InterMetric

log = logging.getLogger("veneur_tpu.localfile")

# column order mirrors reference plugins/s3/csv.go tsvSchema
COLUMNS = ["Name", "Tags", "MetricType", "VeneurHostname", "Interval",
           "Timestamp", "Value", "Partition"]


def _fmt_value(v: float) -> str:
    """Go strconv.FormatFloat(v, 'f', -1, 64): shortest round-tripping
    decimal, never exponent notation — including Go's spellings for the
    non-finite values (NaN/+Inf/-Inf, not Python's nan/inf)."""
    v = float(v)
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return np.format_float_positional(v, trim="-")


def encode_row(m: InterMetric, hostname: str, interval_s: int,
               partition_ts: float):
    """One reference-identical TSV row (csv.go:56 EncodeInterMetricCSV):
    tags braced, counters written as `rate` divided by the interval, the
    Redshift timestamp in the reference's quirky 12-HOUR clock (its Go
    layout uses `03` without AM/PM — replicated for byte parity), and
    the partition from the FLUSH date, not the metric timestamp."""
    if m.type == COUNTER:
        mtype, value = "rate", m.value / interval_s
    elif m.type == GAUGE:
        mtype, value = "gauge", m.value
    else:
        raise ValueError(f"unknown metric type {m.type!r} for CSV")
    ts = time.strftime("%Y-%m-%d %I:%M:%S", time.gmtime(m.timestamp))
    partition = time.strftime("%Y%m%d", time.gmtime(partition_ts))
    return [m.name, "{" + ",".join(m.tags) + "}", mtype, hostname,
            str(int(interval_s)), ts, _fmt_value(value), partition]


def encode_intermetrics_csv(metrics, hostname: str, interval_s: int,
                            delimiter: str = "\t", compress: bool = False,
                            partition_ts: float = None,
                            headers: bool = False) -> bytes:
    """`headers` mirrors the reference's includeHeaders (s3.go
    EncodeInterMetricsCSV): one schema row before the data."""
    if partition_ts is None:
        partition_ts = time.time()
    # sub-second intervals truncate to 0 (factory passes int(seconds));
    # a zero divisor would abort the whole flush on the first counter —
    # clamp to 1s so rates stay finite and every row still lands
    interval_s = int(interval_s) or 1
    buf = io.StringIO()
    w = csv.writer(buf, delimiter=delimiter, lineterminator="\n")
    if headers:
        w.writerow(COLUMNS)
    skipped = 0
    for m in metrics:
        try:
            w.writerow(encode_row(m, hostname, interval_s, partition_ts))
        except ValueError:
            # deliberate deviation: the reference ABORTS the whole flush
            # on the first non-counter/gauge row (csv.go:72 returns err);
            # one status check wiping the interval's S3 object is a
            # failure mode, not a contract — skip-and-count instead
            skipped += 1
    if skipped:
        log.warning("CSV flush skipped %d non-counter/gauge metrics",
                    skipped)
    data = buf.getvalue().encode()
    if compress:
        data = gzip.compress(data)
    return data


class LocalFilePlugin:
    """reference plugins/localfile/localfile.go:32 — appends TSV rows on
    every flush. Registered as a post-flush plugin (plugins/plugins.go:16)."""
    name = "localfile"

    def __init__(self, path: str, hostname: str, interval_s: int = 10,
                 delimiter: str = "\t"):
        self.path = path
        self.hostname = hostname
        self.interval_s = interval_s
        self.delimiter = delimiter

    def flush(self, metrics):
        data = encode_intermetrics_csv(metrics, self.hostname,
                                       self.interval_s, self.delimiter)
        # atomic append: a crash mid-flush must never leave a torn TSV
        # row for downstream loaders (same temp-file + rename discipline
        # as the checkpoint codec; README §Durability)
        from veneur_tpu.utils.atomicio import atomic_append_bytes
        atomic_append_bytes(self.path, data)

    # Plugins are file-bound and low-volume tiers: materializing is fine,
    # but declaring frame support keeps the server's columnar fast path
    # available when this plugin is configured alongside frame sinks.
    accepts_frames = True

    def flush_frame(self, frame):
        self.flush(frame.intermetrics())
