"""Flush-to-file plugin (reference plugins/localfile/localfile.go: TSV
append of every final InterMetric batch) and the CSV encoding shared with
the S3 plugin (reference plugins/s3/csv.go EncodeInterMetricsCSV)."""

from __future__ import annotations

import csv
import gzip
import io
import time

from veneur_tpu.samplers.intermetric import InterMetric

# column order mirrors reference plugins/s3/csv.go tsvSchema
COLUMNS = ["Name", "Tags", "MetricType", "HostName", "Interval",
           "Timestamp", "Value", "Partition"]


def encode_row(m: InterMetric, hostname: str, interval_s: int):
    ts = time.strftime("%Y-%m-%d %H:%M:%S",
                       time.gmtime(m.timestamp))
    partition = time.strftime("%Y%m%d", time.gmtime(m.timestamp))
    return [m.name, ",".join(m.tags), m.type, hostname,
            str(interval_s), ts, repr(float(m.value)), partition]


def encode_intermetrics_csv(metrics, hostname: str, interval_s: int,
                            delimiter: str = "\t", compress: bool = False) -> bytes:
    buf = io.StringIO()
    w = csv.writer(buf, delimiter=delimiter, lineterminator="\n")
    for m in metrics:
        w.writerow(encode_row(m, hostname, interval_s))
    data = buf.getvalue().encode()
    if compress:
        data = gzip.compress(data)
    return data


class LocalFilePlugin:
    """reference plugins/localfile/localfile.go:32 — appends TSV rows on
    every flush. Registered as a post-flush plugin (plugins/plugins.go:16)."""
    name = "localfile"

    def __init__(self, path: str, hostname: str, interval_s: int = 10,
                 delimiter: str = "\t"):
        self.path = path
        self.hostname = hostname
        self.interval_s = interval_s
        self.delimiter = delimiter

    def flush(self, metrics):
        data = encode_intermetrics_csv(metrics, self.hostname,
                                       self.interval_s, self.delimiter)
        with open(self.path, "ab") as f:
            f.write(data)

    # Plugins are file-bound and low-volume tiers: materializing is fine,
    # but declaring frame support keeps the server's columnar fast path
    # available when this plugin is configured alongside frame sinks.
    accepts_frames = True

    def flush_frame(self, frame):
        self.flush(frame.intermetrics())
