"""Generic gRPC span sink + the Falconer wrapper.

reference sinks/grpsink/grpsink.go: a client for the `grpsink.SpanSink`
service (`rpc SendSpan(ssf.SSFSpan) returns (Empty)`, grpc_sink.proto),
with channel-state watching and reconnection handled by grpc-core;
falconer/falconer.go:13 is a named wrapper. Hand-wired method path like
forward/rpc.py — wire-compatible with the reference service.
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Callable

import grpc

from veneur_tpu.proto import ssf_pb2
from veneur_tpu.sinks.base import SpanSink

log = logging.getLogger("veneur_tpu.sinks.grpsink")

METHOD = "/grpsink.SpanSink/SendSpan"


class _Empty:
    """grpsink.Empty — a zero-field message; serializes to b''."""

    @staticmethod
    def SerializeToString() -> bytes:
        return b""

    @staticmethod
    def FromString(_data: bytes) -> "_Empty":
        return _Empty()


class GRPCSpanSink(SpanSink):
    name = "grpc_span_sink"

    def __init__(self, target: str, name: str = None):
        if name:
            self.name = name
        self.target = target
        self._channel = grpc.insecure_channel(target)
        self._send = self._channel.unary_unary(
            METHOD,
            request_serializer=ssf_pb2.SSFSpan.SerializeToString,
            response_deserializer=_Empty.FromString)
        self.sent = 0
        self.errors = 0

    def ingest(self, span) -> None:
        try:
            self._send(span, timeout=9.0)  # per-span sink budget
            self.sent += 1
        except Exception as e:
            self.errors += 1
            log.debug("grpsink send failed: %s", e)

    def close(self):
        self._channel.close()


class FalconerSpanSink(GRPCSpanSink):
    """reference sinks/falconer/falconer.go:13 — grpsink under the
    falconer name."""
    name = "falconer"


def serve_span_sink(handler: Callable, address: str = "127.0.0.1:0"):
    """A SpanSink gRPC server for tests / downstream collectors; calls
    handler(span) per received span. Returns (server, port)."""

    def send_span(request: ssf_pb2.SSFSpan, context):
        handler(request)
        return _Empty()

    rpc_handler = grpc.method_handlers_generic_handler(
        "grpsink.SpanSink",
        {"SendSpan": grpc.unary_unary_rpc_method_handler(
            send_span,
            request_deserializer=ssf_pb2.SSFSpan.FromString,
            response_serializer=lambda e: e.SerializeToString())})
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers((rpc_handler,))
    port = server.add_insecure_port(address)
    server.start()
    return server, port
