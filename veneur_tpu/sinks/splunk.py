"""Splunk HEC span sink (reference sinks/splunk/splunk.go).

Spans become JSON events streamed to the HTTP Event Collector
(`/services/collector/event`, Authorization: Splunk <token>), batched to
`hec_batch_size` with trace-id sampling (splunk.go: keep 1-in-N traces
by trace-id modulo). Indicator spans are never sampled out; one that
WOULD have been dropped is kept with `"partial": true` so indicator
spans with full traces stay searchable (splunk.go:449-456, :490-495).
A span carrying any excluded tag KEY is skipped whole.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from typing import List

from veneur_tpu.sinks.base import SpanSink

log = logging.getLogger("veneur_tpu.sinks.splunk")


class SplunkSpanSink(SpanSink):
    name = "splunk"

    def __init__(self, hec_address: str, token: str, hostname: str,
                 batch_size: int = 100, sample_rate: int = 1,
                 send_timeout: float = 10.0,
                 tls_validate_hostname: str = ""):
        self.url = hec_address.rstrip("/") + "/services/collector/event"
        self.token = token
        # splunk_hec_tls_validate_hostname (splunk.go): HEC endpoints
        # commonly present certs for a name other than the URL host; the
        # TLS handshake validates the chain AND the certificate against
        # this pinned name (never verification-off)
        self._pinned_hostname = tls_validate_hostname or None
        self.hostname = hostname
        self.batch_size = batch_size
        # keep 1-in-N traces (splunk.go splunk_span_sample_rate)
        self.sample_rate = max(1, sample_rate)
        self.send_timeout = send_timeout
        self._buf: List[dict] = []
        self._lock = threading.Lock()
        self.submitted = 0
        self.skipped = 0
        self.excluded_tag_keys: set = set()

    def _event(self, span) -> dict:
        return {
            "host": self.hostname,
            "sourcetype": span.service or "veneur",
            "time": f"{span.start_timestamp / 1e9:.3f}",
            "event": {
                "trace_id": f"{span.trace_id:016x}",
                "id": f"{span.id:016x}",
                "parent_id": f"{span.parent_id:016x}"
                             if span.parent_id else "",
                "name": span.name,
                "service": span.service,
                "indicator": span.indicator,
                "error": span.error,
                "start_timestamp": span.start_timestamp,
                "end_timestamp": span.end_timestamp,
                "duration_ns": span.end_timestamp - span.start_timestamp,
                "tags": dict(span.tags),
            },
        }

    def set_excluded_tags(self, tags) -> None:
        """A span carrying ANY excluded tag KEY is skipped whole
        (splunk.go:462-466) — span exclusion is by key, not prefix. A
        value-qualified entry ("env:prod") can never match a tag KEY;
        the reference silently no-ops there too, but warn so operators
        don't believe an inert rule is active."""
        for t in tags:
            if ":" in t:
                log.warning("splunk excluded tag %r is value-qualified; "
                            "span exclusion matches tag KEYS only and "
                            "this rule will never match", t)
        self.excluded_tag_keys = set(tags)

    def ingest(self, span) -> None:
        # trace-id sampling keeps 1-in-N traces, but INDICATOR spans are
        # never sampled out — a would-drop indicator is kept and marked
        # partial so full traces remain searchable (splunk.go:449-456,
        # :490-495)
        would_drop = (self.sample_rate > 1
                      and span.trace_id % self.sample_rate != 0)
        if would_drop and not span.indicator:
            self.skipped += 1
            return
        if any(k in span.tags for k in self.excluded_tag_keys):
            return
        ev = self._event(span)
        if would_drop:
            ev["event"]["partial"] = True
        with self._lock:
            self._buf.append(ev)
            if len(self._buf) >= self.batch_size:
                batch, self._buf = self._buf, []
            else:
                return
        self._submit(batch)

    def flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf, []
        if batch:
            self._submit(batch)

    def _submit(self, batch: List[dict]):
        # HEC wants newline-delimited event JSON objects
        body = "\n".join(json.dumps(e) for e in batch).encode()
        headers = {"Authorization": f"Splunk {self.token}",
                   "Content-Type": "application/json"}
        try:
            if self._pinned_hostname:
                self._post_pinned(body, headers)
            else:
                req = urllib.request.Request(
                    self.url, data=body, method="POST", headers=headers)
                with urllib.request.urlopen(
                        req, timeout=self.send_timeout) as resp:
                    resp.read()
            self.submitted += len(batch)
        except Exception as e:
            log.error("splunk HEC submit failed: %s", e)

    def _post_pinned(self, body: bytes, headers: dict) -> None:
        """POST over TLS validated against the pinned hostname: the
        handshake uses the pin as server_hostname, so the standard
        verification path (chain + name match) enforces it."""
        import http.client
        import socket
        import ssl
        from urllib.parse import urlparse
        u = urlparse(self.url)
        ctx = ssl.create_default_context()
        raw = socket.create_connection(
            (u.hostname, u.port or 443), timeout=self.send_timeout)
        try:
            tls = ctx.wrap_socket(raw,
                                  server_hostname=self._pinned_hostname)
        except BaseException:
            raw.close()
            raise
        conn = http.client.HTTPConnection(u.hostname, u.port or 443,
                                          timeout=self.send_timeout)
        conn.sock = tls
        try:
            path = u.path or "/"
            conn.request("POST", path, body, headers)
            conn.getresponse().read()
        finally:
            conn.close()
