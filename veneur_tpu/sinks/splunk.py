"""Splunk HEC span sink (reference sinks/splunk/splunk.go).

Spans become JSON events posted to the HTTP Event Collector
(`/services/collector/event`, Authorization: Splunk <token>), batched to
`hec_batch_size` with trace-id sampling (splunk.go: keep 1-in-N traces
by trace-id modulo). Indicator spans are never sampled out; one that
WOULD have been dropped is kept with `"partial": true` so indicator
spans with full traces stay searchable (splunk.go:449-456, :490-495).
A span carrying any excluded tag KEY is skipped whole.

Submission runs on a pool of worker threads (splunk.go:184 submitter
goroutines, splunk_hec_submission_workers): ``ingest()`` only enqueues,
so the span pipeline NEVER blocks on HEC HTTP. Each worker posts a batch
when it reaches `batch_size` or when the batch's connection lifetime
(`max_conn_lifetime` + uniform `conn_lifetime_jitter`, splunk.go:194
batchTimeout) expires — the jitter spreads reconnects across a
load-balanced HEC fleet. Deviation from the reference: with no ingest
timeout the reference's unbuffered channel can block the span worker on
a stalled HEC; here a full queue drops the span and counts it
(``dropped``) instead, because backpressure into the span pipeline is
exactly the failure VERDICT r04 #8 calls out.
"""

from __future__ import annotations

import json
import logging
import queue
import random
import threading
import time
import urllib.request
from typing import List

from veneur_tpu.sinks.base import ResilientSink, SpanSink

log = logging.getLogger("veneur_tpu.sinks.splunk")

_now = time.monotonic


class SplunkSpanSink(ResilientSink, SpanSink):
    name = "splunk"

    def __init__(self, hec_address: str, token: str, hostname: str,
                 batch_size: int = 100, sample_rate: int = 1,
                 send_timeout: float = 10.0,
                 tls_validate_hostname: str = "",
                 workers: int = 1,
                 ingest_timeout: float = 0.0,
                 max_conn_lifetime: float = 10.0,
                 conn_lifetime_jitter: float = 0.0,
                 queue_capacity: int = 0):
        self.url = hec_address.rstrip("/") + "/services/collector/event"
        self.token = token
        # splunk_hec_tls_validate_hostname (splunk.go): HEC endpoints
        # commonly present certs for a name other than the URL host; the
        # TLS handshake validates the chain AND the certificate against
        # this pinned name (never verification-off)
        self._pinned_hostname = tls_validate_hostname or None
        self.hostname = hostname
        self.batch_size = batch_size
        # keep 1-in-N traces (splunk.go splunk_span_sample_rate)
        self.sample_rate = max(1, sample_rate)
        self.send_timeout = send_timeout
        self.ingest_timeout = ingest_timeout
        self.max_conn_lifetime = max(0.1, max_conn_lifetime)
        self.conn_lifetime_jitter = max(0.0, conn_lifetime_jitter)
        self.submitted = 0
        self.skipped = 0
        self.dropped = 0
        # flush() ack waits that expired before the worker answered (a
        # slow POST holding the worker) — the flush returned with that
        # worker's batch possibly still in flight
        self.flush_timeouts = 0
        self.excluded_tag_keys: set = set()
        self.workers = max(1, workers)
        # bounded so a stalled HEC can't grow memory without limit, but
        # deep enough that a burst never outruns the workers in healthy
        # operation (several batches per worker of headroom)
        self._queue: queue.Queue = queue.Queue(
            maxsize=queue_capacity
            or self.workers * max(1, batch_size) + 4096)
        self._stop = threading.Event()
        # per-worker (flush-request, flush-ack) pairs — see flush()
        self._flush_reqs = [(threading.Event(), threading.Event())
                            for _ in range(self.workers)]
        self._flush_serial = threading.Lock()
        self._threads = [
            threading.Thread(target=self._worker, args=(i,), daemon=True,
                             name=f"splunk-hec-{i}")
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    def _event(self, span) -> dict:
        return {
            "host": self.hostname,
            "sourcetype": span.service or "veneur",
            "time": f"{span.start_timestamp / 1e9:.3f}",
            "event": {
                "trace_id": f"{span.trace_id:016x}",
                "id": f"{span.id:016x}",
                "parent_id": f"{span.parent_id:016x}"
                             if span.parent_id else "",
                "name": span.name,
                "service": span.service,
                "indicator": span.indicator,
                "error": span.error,
                "start_timestamp": span.start_timestamp,
                "end_timestamp": span.end_timestamp,
                "duration_ns": span.end_timestamp - span.start_timestamp,
                "tags": dict(span.tags),
            },
        }

    def set_excluded_tags(self, tags) -> None:
        """A span carrying ANY excluded tag KEY is skipped whole
        (splunk.go:462-466) — span exclusion is by key, not prefix. A
        value-qualified entry ("env:prod") can never match a tag KEY;
        the reference silently no-ops there too, but warn so operators
        don't believe an inert rule is active."""
        for t in tags:
            if ":" in t:
                log.warning("splunk excluded tag %r is value-qualified; "
                            "span exclusion matches tag KEYS only and "
                            "this rule will never match", t)
        self.excluded_tag_keys = set(tags)

    def ingest(self, span) -> None:
        # trace-id sampling keeps 1-in-N traces, but INDICATOR spans are
        # never sampled out — a would-drop indicator is kept and marked
        # partial so full traces remain searchable (splunk.go:449-456,
        # :490-495)
        would_drop = (self.sample_rate > 1
                      and span.trace_id % self.sample_rate != 0)
        if would_drop and not span.indicator:
            self.skipped += 1
            return
        if any(k in span.tags for k in self.excluded_tag_keys):
            return
        ev = self._event(span)
        if would_drop:
            ev["event"]["partial"] = True
        # enqueue only — HTTP happens on the worker pool. A full queue
        # (stalled HEC) drops-and-counts rather than backpressuring the
        # span pipeline (splunk.go:505-509 counts the same way when its
        # ingest deadline fires).
        try:
            if self.ingest_timeout > 0:
                self._queue.put(ev, timeout=self.ingest_timeout)
            else:
                self._queue.put_nowait(ev)
        except queue.Full:
            self.dropped += 1

    def flush(self) -> None:
        """Synchronize: every worker posts its in-progress batch plus
        everything queued at this moment (splunk.go:160 Flush → one sync
        signal PER worker + WaitGroup — a shared-queue sentinel could be
        eaten twice by one idle worker while another holds a batch).
        Serialized so a concurrent caller can't clear an ack between a
        worker's req.clear() and ack.set()."""
        with self._flush_serial:
            for req, ack in self._flush_reqs:
                ack.clear()
                req.set()
            # Event.wait returns False on timeout — a dropped result
            # here silently reported a complete sync the stalled worker
            # never confirmed. Collect each verdict; an expired wait is
            # counted and warned so operators see the partial flush.
            timed_out = [idx
                         for idx, (_req, ack) in enumerate(self._flush_reqs)
                         if not ack.wait(self.send_timeout)]
            if timed_out:
                self.flush_timeouts += len(timed_out)
                log.warning(
                    "splunk flush: %d of %d workers did not ack within "
                    "%.1fs (workers %s; batches may still be in flight)",
                    len(timed_out), len(self._flush_reqs),
                    self.send_timeout, timed_out)

    def stop(self) -> None:
        # flush FIRST: once _stop is visible an idle worker exits at the
        # top of its loop and would never serve the flush request
        self.flush()
        self._stop.set()

    def _worker(self, idx: int) -> None:
        """One submission worker (splunk.go:184 submitter): accumulate a
        batch until batch_size, a flush request, or the batch lifetime
        (max_conn_lifetime + jitter) expires, then POST it. The short
        get() timeout is the Python stand-in for Go's select over the
        ingest and sync channels."""
        req, ack = self._flush_reqs[idx]
        while True:
            if self._stop.is_set():
                # final drain: even if stop() raced ahead of a pending
                # flush request (e.g. an ack wait expired while this
                # worker sat in a slow POST), everything queued is
                # posted and the request acknowledged before exit — no
                # silent span loss, no permanently-wedged flush()
                batch = []
                while True:
                    try:
                        batch.append(self._queue.get_nowait())
                    except queue.Empty:
                        break
                    if len(batch) >= self.batch_size:
                        self._submit(batch)
                        batch = []
                if batch:
                    self._submit(batch)
                if req.is_set():
                    req.clear()
                    ack.set()
                return
            lifetime = self.max_conn_lifetime
            if self.conn_lifetime_jitter > 0:
                lifetime += random.uniform(0, self.conn_lifetime_jitter)
            deadline = _now() + lifetime
            batch: List[dict] = []
            while True:
                if req.is_set():
                    break
                left = deadline - _now()
                if left <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=min(left, 0.05)))
                except queue.Empty:
                    continue
                if len(batch) >= self.batch_size:
                    break
            if req.is_set():
                # drain everything queued before the flush call, posting
                # full batches as they fill, then acknowledge
                while True:
                    try:
                        batch.append(self._queue.get_nowait())
                    except queue.Empty:
                        break
                    if len(batch) >= self.batch_size:
                        self._submit(batch)
                        batch = []
                if batch:
                    self._submit(batch)
                req.clear()
                ack.set()
                continue
            if batch:
                self._submit(batch)

    def _submit(self, batch: List[dict]):
        # HEC wants newline-delimited event JSON objects
        body = "\n".join(json.dumps(e) for e in batch).encode()
        headers = {"Authorization": f"Splunk {self.token}",
                   "Content-Type": "application/json"}

        def once():
            if self._pinned_hostname:
                self._post_pinned(body, headers)
            else:
                req = urllib.request.Request(
                    self.url, data=body, method="POST", headers=headers)
                with urllib.request.urlopen(
                        req, timeout=self.send_timeout) as resp:
                    resp.read()

        try:
            self.resilient_post(once, what="hec")
            self.submitted += len(batch)
        except Exception as e:
            log.error("splunk HEC submit failed: %s", e)

    def _post_pinned(self, body: bytes, headers: dict) -> None:
        """POST over TLS validated against the pinned hostname: the
        handshake uses the pin as server_hostname, so the standard
        verification path (chain + name match) enforces it."""
        import http.client
        import socket
        import ssl
        from urllib.parse import urlparse
        u = urlparse(self.url)
        ctx = ssl.create_default_context()
        raw = socket.create_connection(
            (u.hostname, u.port or 443), timeout=self.send_timeout)
        try:
            tls = ctx.wrap_socket(raw,
                                  server_hostname=self._pinned_hostname)
        except BaseException:
            raw.close()
            raise
        conn = http.client.HTTPConnection(u.hostname, u.port or 443,
                                          timeout=self.send_timeout)
        conn.sock = tls
        try:
            path = u.path or "/"
            conn.request("POST", path, body, headers)
            conn.getresponse().read()
        finally:
            conn.close()
