"""No-op sink (reference sinks/blackhole/blackhole.go) — the benchmark and
test target (BASELINE config 1 flushes to blackhole)."""

from veneur_tpu.sinks.base import MetricSink, SpanSink


class BlackholeMetricSink(MetricSink):
    name = "blackhole"

    def flush(self, metrics):
        pass


class BlackholeSpanSink(SpanSink):
    name = "blackhole"

    def ingest(self, span):
        pass
