"""No-op sink (reference sinks/blackhole/blackhole.go) — the benchmark and
test target (BASELINE config 1 flushes to blackhole)."""

from veneur_tpu.sinks.base import MetricSink, SpanSink


class BlackholeMetricSink(MetricSink):
    name = "blackhole"

    def __init__(self):
        self.frames_rows = 0  # benchmark introspection

    def flush(self, metrics):
        pass

    def flush_frame(self, frame):
        self.frames_rows += len(frame)


class BlackholeSpanSink(SpanSink):
    name = "blackhole"

    def ingest(self, span):
        pass
