"""Tag-frequency span sink: count-min heavy hitters over the span firehose.

No reference counterpart — this is the sketch consumer BASELINE config 5
calls for (10M-tag SSF span stream → per-interval top-K tag frequencies).
A span sink (SURVEY §2.5 fan-out: every span visits every sink) that feeds
`tag_key:value` strings into the device count-min sketch
(veneur_tpu/ops/countmin.py) and, at flush, reports the interval's top-K
as SSF samples through the server's own trace client — so the results ride
the normal self-telemetry loop-back into the metric pipeline and out every
metric sink, exactly like veneur.* counters.

Batching: members are buffered per worker call and shipped to the device in
fixed-size batches (amortizes dispatch; SURVEY §7 "hardest part #2" says
≥64k samples/dispatch for the firehose — the default here is smaller so
light spans traffic still flushes promptly, the batch size is config).
Thread safety: span pipelines may run several workers; buffer + sketch
updates are lock-guarded (the device update itself is jitted + functional).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional, Sequence

from veneur_tpu.ops.countmin import (
    DEFAULT_DEPTH, DEFAULT_WIDTH, HeavyHitters)

log = logging.getLogger("veneur_tpu.sinks.tagfreq")


class TagFrequencySink:
    """SpanSink tracking heavy-hitter tag values per flush interval."""

    name = "tag_frequency"

    def __init__(self, report: Optional[Callable[[List], None]] = None,
                 tag_keys: Sequence[str] = (), top_k: int = 100,
                 depth: int = DEFAULT_DEPTH, width: int = DEFAULT_WIDTH,
                 batch_size: int = 4096,
                 metric_name: str = "veneur.span.tag_frequency"):
        self.report = report
        self.tag_keys = set(tag_keys)
        self.top_k = top_k
        self.batch_size = batch_size
        self.metric_name = metric_name
        self.hh = HeavyHitters(top_k, depth, width)
        self._buf: List[bytes] = []
        self._lock = threading.Lock()
        self.spans_seen = 0
        self.members_seen = 0

    def start(self):
        pass

    def _span_members(self, span) -> List[bytes]:
        return [f"{k}:{v}".encode() for k, v in span.tags.items()
                if not self.tag_keys or k in self.tag_keys]

    def _ingest_members(self, members: List[bytes], n_spans: int) -> None:
        """Single buffering path for both ingest flavors. Atomic per the
        SpanPipeline retry contract: the (possibly raising) device update
        runs BEFORE any state mutation, so a failure leaves the sink
        exactly as it was and per-span redelivery cannot double-count."""
        if not members:
            return
        with self._lock:
            merged = self._buf + members
            if len(merged) >= self.batch_size:
                self.hh.update(merged)   # may raise -> nothing mutated
                self._buf = []
            else:
                self._buf = merged
            self.spans_seen += n_spans
            self.members_seen += len(members)

    def ingest(self, span) -> None:
        members = self._span_members(span)
        self._ingest_members(members, 1 if members else 0)

    def ingest_many(self, spans) -> None:
        """Batched span-worker path: one lock round-trip per batch."""
        members: List[bytes] = []
        n_spans = 0
        for span in spans:
            m = self._span_members(span)
            if m:
                n_spans += 1
                members.extend(m)
        self._ingest_members(members, n_spans)

    def _drain_locked(self):
        if self._buf:
            self.hh.update(self._buf)
            self._buf = []

    def flush(self) -> List:
        """Report the interval's top-K and reset (flush-scoped state, like
        every other sketch in the pipeline). Returns the samples for tests
        and callers without a report callback."""
        from veneur_tpu.samplers import ssf_samples
        with self._lock:
            self._drain_locked()
            top = self.hh.top(self.top_k)
            total = self.hh.total
            self.hh.reset()
        samples = []
        for member, count in top:
            kv = member.decode("utf-8", "replace")
            samples.append(ssf_samples.gauge(
                self.metric_name, float(count), {"tag": kv}))
        if samples:
            samples.append(ssf_samples.gauge(
                self.metric_name + ".total", float(total)))
        if self.report is not None and samples:
            try:
                self.report(samples)
            except Exception as e:
                log.warning("tag-frequency report failed: %s", e)
        return samples
