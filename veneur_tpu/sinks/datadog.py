"""Datadog metric sink — batched JSON series posts.

reference sinks/datadog/datadog.go: `DDMetric` JSON bodies posted to
`{api}/api/v1/series?api_key=...`, chunked to `datadog_flush_max_per_body`
points per POST (:112-160), name-prefix drops and per-prefix tag exclusion
(:256+), events/service checks via FlushOtherSamples (:162). Uses urllib —
no external HTTP dependency — with zlib deflate like the reference's
compressed posts (http/http.go PostHelper).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
import zlib
from typing import List

from veneur_tpu.samplers.intermetric import COUNTER, STATUS, InterMetric
from veneur_tpu.sinks.base import (MetricSink, ResilientSink,
                                   filter_acceptable)

log = logging.getLogger("veneur_tpu.sinks.datadog")


class DatadogMetricSink(ResilientSink, MetricSink):
    name = "datadog"

    def __init__(self, api_key: str, hostname: str, api_url: str,
                 interval_s: float = 10.0, flush_max_per_body: int = 25000,
                 tags: List[str] = (), metric_name_prefix_drops: List[str] = (),
                 exclude_tags_prefix_by_prefix_metric: dict = None):
        self.api_key = api_key
        self.hostname = hostname
        self.api_url = api_url.rstrip("/")
        self.interval_s = interval_s
        self.flush_max_per_body = flush_max_per_body
        self.tags = list(tags)
        self.prefix_drops = list(metric_name_prefix_drops)
        self.prefix_tag_excludes = dict(
            exclude_tags_prefix_by_prefix_metric or {})

    # -- serialization ------------------------------------------------------
    def _add(self, series, checks, name, ts, value, mtype, tags, host,
             message, sink_tags):
        """The ONE serialization both flush paths share (reference
        datadog.go:256 finalizeMetrics): `host:`/`device:` magic tags
        override the metric's hostname / set device_name and are removed
        from the tag list (checked BEFORE tag exclusions, like the
        reference); STATUS metrics become Datadog service checks; counters
        become rates divided by the flush interval. One deliberate
        refinement over the reference (which only consults the sink-level
        hostname): an InterMetric-carried hostname — a proxied peer's —
        ranks between the magic tag and the sink default."""
        magic_host = device = None
        kept = []
        for t in tags:
            if t.startswith("host:"):
                magic_host = t[5:]
            elif t.startswith("device:"):
                device = t[7:]
            else:
                kept.append(t)
        kept = self.strip_excluded(kept)
        for prefix, excludes in self.prefix_tag_excludes.items():
            if name.startswith(prefix):
                kept = [t for t in kept
                        if not any(t == e or t.startswith(e + ":")
                                   for e in excludes)]
        hostname = magic_host or host or self.hostname
        all_tags = kept + sink_tags
        if mtype == STATUS:
            # a non-finite status (unvalidated f32 lane) must degrade to
            # UNKNOWN(3), not abort the whole interval's flush
            status = int(value) if value == value and abs(value) != \
                float("inf") else 3
            checks.append({
                "check": name, "status": status,
                "host_name": hostname, "timestamp": ts,
                "tags": all_tags, "message": message})
            return
        dd = {
            "metric": name,
            "type": "gauge",
            "points": [[ts, value]],
            "host": hostname,
            "tags": all_tags,
        }
        if mtype == COUNTER:
            # Datadog rates: value divided by the flush interval, with the
            # interval attached so count rollups reconstruct the original
            # (reference datadog.go:375 Interval)
            dd["type"] = "rate"
            dd["points"] = [[ts, value / self.interval_s]]
            dd["interval"] = int(self.interval_s)
        if device:
            dd["device_name"] = device
        series.append(dd)

    # -- flush --------------------------------------------------------------
    def flush(self, metrics):
        metrics = filter_acceptable(metrics, self.name)
        series, checks = [], []
        # sink-level tags pass the operator's exclusions too (the
        # reference filters dd.tags the same way) — invariant per flush
        sink_tags = self.strip_excluded(self.tags)
        for m in metrics:
            if any(m.name.startswith(p) for p in self.prefix_drops):
                continue
            self._add(series, checks, m.name, m.timestamp, m.value,
                      m.type, m.tags, m.hostname, m.message, sink_tags)
        self._post_series(series)
        self._post_checks(checks)

    def flush_frame(self, frame):
        """Columnar flush: DDMetric dicts straight from the frame's
        prepared rows — no InterMetric materialization between the
        flusher and the JSON body (the per-object detour is ~2us/metric
        at the 10M-key scale; see flusher.MetricFrame). Same emission
        rules as flush(): sink routing, prefix drops, shared _add."""
        drops = self.prefix_drops
        ts = frame.timestamp
        series, checks = [], []
        sink_tags = self.strip_excluded(self.tags)
        for name, value, mtype, msg, tags, sinks, host in frame.rows():
            if drops and any(name.startswith(p) for p in drops):
                continue
            if sinks is not None and self.name not in sinks:
                continue
            self._add(series, checks, name, ts, value, mtype, tags, host,
                      msg, sink_tags)
        self._post_series(series)
        self._post_checks(checks)

    def _post_json(self, path, payload, what):
        """The one deflate-JSON POST used by series, checks and events,
        run under the sink's retry/breaker harness (a passthrough when
        unconfigured); terminal errors are logged, never fatal."""
        url = f"{self.api_url}{path}?api_key={self.api_key}"
        req = urllib.request.Request(
            url, data=zlib.compress(json.dumps(payload).encode()),
            method="POST",
            headers={"Content-Type": "application/json",
                     "Content-Encoding": "deflate"})

        def once():
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()

        try:
            self.resilient_post(once, what=what)
        except Exception as e:
            log.error("datadog %s flush failed: %s", what, e)

    def _post_checks(self, checks):
        """Service checks go to the check_run API (datadog.go:122),
        chunked like series so one giant body can't be rejected whole."""
        for i in range(0, len(checks), self.flush_max_per_body):
            self._post_json("/api/v1/check_run",
                            checks[i:i + self.flush_max_per_body],
                            "check_run")

    def _post_series(self, series):
        if not series:
            return
        chunks = [series[i:i + self.flush_max_per_body]
                  for i in range(0, len(series), self.flush_max_per_body)]
        # parallel chunk posts, like the reference's per-chunk goroutines
        # (datadog.go:145-155 flushPart workers)
        threads = [threading.Thread(target=self._post_chunk, args=(c,))
                   for c in chunks[1:]]
        for t in threads:
            t.start()
        self._post_chunk(chunks[0])
        for t in threads:
            t.join()

    def _post_chunk(self, series):
        self._post_json("/api/v1/series", {"series": series}, "series")

    def flush_other_samples(self, samples):
        """DogStatsD events → Datadog events API: the vdogstatsd_* conduit
        tags map back onto event fields (reference datadog.go:162
        FlushOtherSamples / parseMetricsFromSSFSamples)."""
        events = []
        for s in samples:
            tags = dict(s.tags) if s.tags else {}
            if "vdogstatsd_ev" not in tags:
                continue
            ev = {
                "title": s.name,
                "text": s.message,
                "date_happened": s.timestamp,
                "tags": [f"{k}:{v}" for k, v in tags.items()
                         if not k.startswith("vdogstatsd")],
            }
            field_map = {"vdogstatsd_at": "alert_type",
                         "vdogstatsd_pri": "priority",
                         "vdogstatsd_hostname": "host",
                         "vdogstatsd_st": "source_type_name",
                         "vdogstatsd_ak": "aggregation_key"}
            for tag_key, ev_key in field_map.items():
                if tags.get(tag_key):
                    ev[ev_key] = tags[tag_key]
            events.append(ev)
        if events:
            self._post_json("/intake", {"events": events}, "event")
