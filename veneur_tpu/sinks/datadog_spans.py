"""Datadog trace (span) sink — reference datadog.go:410-498 span half.

Spans buffer in a bounded ring and flush as `[[DatadogTraceSpan...]]`
grouped by trace id, POSTed to `{trace_api}/v0.3/traces` (the trace-agent
API the reference targets).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from collections import deque

from veneur_tpu.sinks.base import SpanSink

log = logging.getLogger("veneur_tpu.sinks.datadog")


class DatadogSpanSink(SpanSink):
    name = "datadog"

    def __init__(self, trace_api_address: str, buffer_size: int = 16384):
        self.trace_api = trace_api_address.rstrip("/")
        # bounded ring: oldest spans drop when full (datadog.go ring buffer)
        self.buffer = deque(maxlen=buffer_size)
        self._lock = threading.Lock()
        self.flushed = 0

    def _dd_span(self, span) -> dict:
        duration = span.end_timestamp - span.start_timestamp
        return {
            "trace_id": span.trace_id & ((1 << 64) - 1),
            "span_id": span.id & ((1 << 64) - 1),
            "parent_id": span.parent_id & ((1 << 64) - 1),
            "start": span.start_timestamp,
            "duration": duration,
            "name": span.name,
            "resource": span.tags.get("resource", span.name),
            "service": span.service,
            "type": span.tags.get("type", "custom"),
            "error": 1 if span.error else 0,
            "meta": dict(span.tags),
        }

    def ingest(self, span) -> None:
        from veneur_tpu.protocol.wire import valid_trace
        # metrics-only carrier spans (self-telemetry, emit -ssf metrics)
        # are not traces (reference datadog.go Ingest -> ValidateTrace)
        if not valid_trace(span):
            return
        with self._lock:
            self.buffer.append(self._dd_span(span))

    def flush(self) -> None:
        with self._lock:
            spans, self.buffer = list(self.buffer), deque(
                maxlen=self.buffer.maxlen)
        if not spans:
            return
        traces = {}
        for s in spans:
            traces.setdefault(s["trace_id"], []).append(s)
        body = json.dumps(list(traces.values())).encode()
        req = urllib.request.Request(
            f"{self.trace_api}/v0.3/traces", data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                resp.read()
            self.flushed += len(spans)
        except Exception as e:
            log.error("datadog trace flush failed: %s", e)
