"""AWS X-Ray span sink (reference sinks/xray/xray.go).

UDP JSON segment documents to the X-Ray daemon, each datagram prefixed
with `{"format": "json", "version": 1}\\n` (xray.go:22 segmentHeader).
Trace ids use the X-Ray `1-<epoch hex>-<24 hex>` format; %-based sampling
on trace id; annotations from an allowlisted tag set (xray.go
xray_annotation_tags).
"""

from __future__ import annotations

import json
import logging
import socket
import zlib
from typing import List

from veneur_tpu.sinks.base import SpanSink

log = logging.getLogger("veneur_tpu.sinks.xray")

SEGMENT_HEADER = b'{"format": "json", "version": 1}\n'


class XRaySpanSink(SpanSink):
    name = "xray"

    def __init__(self, daemon_address: str = "127.0.0.1:2000",
                 sample_percentage: float = 100.0,
                 annotation_tags: List[str] = ()):
        host, _, port = daemon_address.partition(":")
        self.addr = (host or "127.0.0.1", int(port or 2000))
        self.sample_percentage = sample_percentage
        self.annotation_tags = list(annotation_tags)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sent = 0
        self.skipped = 0

    @staticmethod
    def trace_id(span) -> str:
        """xray.go:262 CalculateTraceID. X-Ray only assembles segments
        sharing one trace id, so the epoch half comes from the trace's
        ROOT start when the client sent it, else from the span start
        bucketed to ~4.3 min (low byte cleared) so siblings within the
        window agree. Same best-effort contract as the reference: traces
        whose clients mix sending/omitting root_start, or whose spans
        straddle a bucket boundary, can still shear — root_start from
        every client is the reliable path."""
        epoch = getattr(span, "root_start_timestamp", 0) // int(1e9)
        if epoch == 0:
            epoch = (span.start_timestamp // int(1e9)) & 0xFFFFFFFFFFFF00
        return (f"1-{epoch & 0xFFFFFFFF:08x}-"
                f"{span.trace_id & ((1 << 96) - 1):024x}")

    def ingest(self, span) -> None:
        # the sample decision hashes the DECIMAL trace id with CRC32
        # against pct-of-maxuint32 (xray.go:155-160): every veneur
        # instance keeps the SAME traces, so distributed traces stay
        # complete — a plain modulo would shear them apart
        hash_key = zlib.crc32(str(span.trace_id).encode()) & 0xFFFFFFFF
        if hash_key > int(self.sample_percentage * 0xFFFFFFFF / 100):
            self.skipped += 1
            return
        annotations = {k: v for k, v in span.tags.items()
                       if k in self.annotation_tags}
        segment = {
            "name": (span.service or "unknown")[:200],
            "id": f"{span.id & ((1 << 64) - 1):016x}",
            "trace_id": self.trace_id(span),
            "start_time": span.start_timestamp / 1e9,
            "end_time": span.end_timestamp / 1e9,
            "namespace": "remote",
            "error": bool(span.error),
            "annotations": annotations,
            "metadata": {"name": span.name},
        }
        if span.parent_id:
            segment["parent_id"] = f"{span.parent_id & ((1 << 64) - 1):016x}"
        try:
            self.sock.sendto(SEGMENT_HEADER + json.dumps(segment).encode(),
                             self.addr)
            self.sent += 1
        except OSError as e:
            log.error("xray send failed: %s", e)
