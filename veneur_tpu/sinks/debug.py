"""Log-everything sink (reference sinks/debug/debug.go: gated on
debug_flushed_metrics / debug_ingested_spans)."""

from __future__ import annotations

import logging

from veneur_tpu.sinks.base import MetricSink, SpanSink, filter_acceptable

log = logging.getLogger("veneur_tpu.sinks.debug")


class DebugMetricSink(MetricSink):
    name = "debug"

    def __init__(self):
        self.flushed = []  # kept for tests/introspection, like channel sinks

    # frame flushes use the base default: materialize (memoized) — debug
    # keeps full objects for introspection by design

    def flush(self, metrics):
        metrics = filter_acceptable(metrics, self.name)
        self.flushed.extend(metrics)
        for m in metrics:
            log.info("flushed metric name=%s type=%s value=%s tags=%s",
                     m.name, m.type, m.value, ",".join(m.tags))


class DebugSpanSink(SpanSink):
    name = "debug"

    def __init__(self):
        self.spans = []

    def ingest(self, span):
        self.spans.append(span)
        log.info("ingested span service=%s name=%s trace_id=%d",
                 span.service, span.name, span.trace_id)
