"""Metric-extraction span sink: re-injects span-derived metrics into the
aggregation pipeline (reference sinks/ssfmetrics/metrics.go:44
NewMetricExtractionSink — always the first span sink).

Extracts: embedded SSF samples (ConvertMetrics), indicator-span SLI timers
(ConvertIndicatorMetrics), and sampled span-name uniqueness Sets
(ConvertSpanUniquenessMetrics)."""

from __future__ import annotations

import logging
from typing import Callable

from veneur_tpu.protocol.wire import valid_trace
from veneur_tpu.samplers import parser
from veneur_tpu.sinks.base import SpanSink

log = logging.getLogger("veneur_tpu.sinks.ssfmetrics")


class MetricExtractionSink(SpanSink):
    name = "metric_extraction"

    def __init__(self, process_metrics: Callable,
                 indicator_timer_name: str = "",
                 objective_timer_name: str = "",
                 uniqueness_rate: float = 0.01):
        """process_metrics: callable taking a list of UDPMetrics (routed to
        the aggregation pipeline, metrics.go:65-69)."""
        self.process_metrics = process_metrics
        self.indicator_timer_name = indicator_timer_name
        self.objective_timer_name = objective_timer_name
        self.uniqueness_rate = uniqueness_rate
        self.invalid_samples = 0

    def _extract(self, span, out: list) -> int:
        """Returns the span's invalid-sample count instead of mutating
        state — callers fold it in only after the pipeline hand-off
        succeeds (SpanPipeline atomicity contract)."""
        metrics, invalid = parser.convert_metrics(span)
        out.extend(metrics)
        # indicator + uniqueness extraction only for valid trace spans;
        # metric-carrier-only packets stop here (metrics.go:111-114)
        if valid_trace(span):
            if self.indicator_timer_name or self.objective_timer_name:
                try:
                    out.extend(parser.convert_indicator_metrics(
                        span, self.indicator_timer_name,
                        self.objective_timer_name))
                except parser.ParseError as e:
                    log.debug("indicator conversion failed: %s", e)
            if self.uniqueness_rate > 0:
                out.extend(
                    parser.convert_span_uniqueness_metrics(
                        span, self.uniqueness_rate))
        return len(invalid)

    def ingest(self, span) -> None:
        metrics: list = []
        invalid = self._extract(span, metrics)
        if metrics:
            self.process_metrics(metrics)
        self.invalid_samples += invalid

    def ingest_many(self, spans) -> None:
        """One pipeline hand-off per worker batch instead of per span.
        Atomic per the SpanPipeline contract: nothing — not even the
        invalid-sample counter — mutates until the single
        process_metrics call has succeeded."""
        metrics: list = []
        invalid = 0
        for span in spans:
            invalid += self._extract(span, metrics)
        if metrics:
            self.process_metrics(metrics)
        self.invalid_samples += invalid
