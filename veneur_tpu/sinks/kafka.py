"""Kafka metric + span sink (reference sinks/kafka/kafka.go).

The reference uses sarama async producers; this image carries no Kafka
client library, so the producer is injectable: any callable
`produce(topic: str, key: bytes, value: bytes)` (e.g.
confluent_kafka.Producer(...).produce). Without one, construction tries
`kafka-python` / `confluent_kafka` and raises a clear error if neither
exists — the factory only wires this sink when kafka_broker is set.

Serialization mirrors the reference: metrics as JSON, spans as protobuf or
JSON (kafka_span_serialization_format), hash-partitioned by trace id via
the message key (kafka.go:228-306), span sampling by tag/rate.
"""

from __future__ import annotations

import json
import logging
import math
from typing import Callable, Optional

from veneur_tpu.reliability.policy import CircuitOpenError
from veneur_tpu.sinks.base import (MetricSink, ResilientSink, SpanSink,
                                   filter_acceptable)

log = logging.getLogger("veneur_tpu.sinks.kafka")


def _default_producer(broker: str) -> Callable:
    try:
        from confluent_kafka import Producer  # type: ignore

        p = Producer({"bootstrap.servers": broker})

        def produce(topic, key, value):
            p.produce(topic, key=key, value=value)
            p.poll(0)

        return produce
    except ImportError:
        pass
    try:
        from kafka import KafkaProducer  # type: ignore

        p = KafkaProducer(bootstrap_servers=broker)
        return lambda topic, key, value: p.send(topic, key=key, value=value)
    except ImportError:
        raise RuntimeError(
            "kafka sink requires confluent_kafka or kafka-python, or an "
            "injected producer callable")


class KafkaMetricSink(ResilientSink, MetricSink):
    name = "kafka"

    def __init__(self, broker: str, metric_topic: str,
                 check_topic: str = "", producer: Optional[Callable] = None):
        self.metric_topic = metric_topic
        self.check_topic = check_topic
        self.produce = producer or _default_producer(broker)
        self.flushed = 0

    # the reference produces json.Marshal(InterMetric) with NO field
    # tags (kafka.go:205): Go-default capitalized keys, the MetricType
    # iota as a NUMBER, and Sinks as a key-only map (null = every sink).
    # Consumers built against that schema must keep working.
    _TYPE_NUM = {"counter": 0, "gauge": 1, "status": 2}

    def flush(self, metrics):
        for m in filter_acceptable(metrics, self.name):
            if not math.isfinite(m.value):
                # Go's json.Marshal errors on non-finite floats, and the
                # reference ABORTS the whole flush on that error
                # (kafka.go:205-210). Deliberate deviation: drop only the
                # bad message — one NaN must not wipe the interval's batch
                # — while still never emitting Python's bare NaN literal,
                # which strict consumers reject.
                log.warning("kafka: dropping non-finite metric %s", m.name)
                continue
            topic = (self.check_topic
                     if m.type == "status" and self.check_topic
                     else self.metric_topic)
            value = json.dumps({
                "Name": m.name, "Timestamp": m.timestamp,
                "Value": m.value, "Tags": list(m.tags),
                "Type": self._TYPE_NUM.get(m.type, 1),
                "Message": m.message, "HostName": m.hostname,
                "Sinks": ({s: {} for s in sorted(m.sinks)}
                          if m.sinks is not None else None),
            }).encode()
            try:
                self.resilient_post(
                    lambda: self.produce(topic, m.name.encode(), value),
                    what="produce")
                self.flushed += 1
            except CircuitOpenError as e:
                # the breaker refuses every remaining message in the
                # batch too — one warning, not thousands of error lines
                log.warning("kafka: %s; skipping rest of batch", e)
                break
            except Exception as e:
                log.error("kafka produce failed: %s", e)


class KafkaSpanSink(ResilientSink, SpanSink):
    name = "kafka"

    def __init__(self, broker: str, span_topic: str,
                 serialization: str = "protobuf",
                 sample_rate_percent: int = 100, sample_tag: str = "",
                 producer: Optional[Callable] = None):
        self.span_topic = span_topic
        self.serialization = serialization
        self.sample_rate_percent = sample_rate_percent
        self.sample_tag = sample_tag
        self.produce = producer or _default_producer(broker)
        self.sent = 0
        self.skipped = 0

    def ingest(self, span) -> None:
        # sampling: by tag value hash when a sample tag is configured,
        # else by trace id (kafka.go:228-306). The hash must be stable
        # across restarts and fleet members so a sampled trace stays whole
        # — builtin hash() is PYTHONHASHSEED-randomized, fnv1a is not.
        if self.sample_rate_percent < 100:
            from veneur_tpu.utils.hashing import fnv1a_64
            basis = (fnv1a_64(span.tags.get(self.sample_tag, "").encode())
                     if self.sample_tag else span.trace_id)
            if (basis % 100) >= self.sample_rate_percent:
                self.skipped += 1
                return
        key = b"%016x" % (span.trace_id & ((1 << 64) - 1))
        if self.serialization == "json":
            value = json.dumps({
                "trace_id": span.trace_id, "id": span.id,
                "parent_id": span.parent_id, "name": span.name,
                "service": span.service, "error": span.error,
                "start_timestamp": span.start_timestamp,
                "end_timestamp": span.end_timestamp,
                "tags": dict(span.tags),
            }).encode()
        else:
            value = span.SerializeToString()
        try:
            self.resilient_post(
                lambda: self.produce(self.span_topic, key, value),
                what="produce")
            self.sent += 1
        except Exception as e:
            log.error("kafka span produce failed: %s", e)
