"""Sink interfaces (reference sinks/sinks.go:32-103) and the registry the
server wires at startup (reference server.go:472-678)."""

from veneur_tpu.sinks.base import MetricSink, SpanSink  # noqa: F401
