"""LightStep span sink (reference sinks/lightstep/lightstep.go).

The reference pools `lightstep_num_clients` opentracing clients and
round-robins spans by trace id (lightstep.go:126-204). The LightStep
tracer library is not part of this image, so the client factory is
injectable (any object with `.report(span_dict)`); without one,
construction requires the `lightstep` package and raises cleanly
otherwise — the factory only wires this sink when an access token is
configured.
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional

from veneur_tpu.sinks.base import SpanSink

log = logging.getLogger("veneur_tpu.sinks.lightstep")


class LightStepSpanSink(SpanSink):
    name = "lightstep"

    def __init__(self, access_token: str, collector_host: str = "",
                 num_clients: int = 1,
                 client_factory: Optional[Callable] = None):
        if client_factory is None:
            try:
                import lightstep  # type: ignore
            except ImportError:
                raise RuntimeError(
                    "lightstep sink requires the lightstep package or an "
                    "injected client_factory")

            def client_factory():
                return lightstep.Tracer(access_token=access_token,
                                        collector_host=collector_host
                                        or None)
        self.clients: List = [client_factory() for _ in range(
            max(1, num_clients))]
        self.sent = 0

    def _client_for(self, span):
        # round-robin by trace id (lightstep.go:126-204)
        return self.clients[span.trace_id % len(self.clients)]

    def ingest(self, span) -> None:
        client = self._client_for(span)
        duration_us = (span.end_timestamp - span.start_timestamp) / 1e3
        if hasattr(client, "report"):
            client.report({
                "operation_name": span.name, "service": span.service,
                "trace_id": span.trace_id, "span_id": span.id,
                "parent_id": span.parent_id,
                "start_us": span.start_timestamp / 1e3,
                "duration_us": duration_us, "error": span.error,
                "tags": dict(span.tags)})
        else:  # a real lightstep.Tracer
            ls = client.start_span(operation_name=span.name,
                                   start_time=span.start_timestamp / 1e9)
            for k, v in span.tags.items():
                ls.set_tag(k, v)
            ls.set_tag("error", span.error)
            ls.finish(finish_time=span.end_timestamp / 1e9)
        self.sent += 1

    def flush(self) -> None:
        for c in self.clients:
            if hasattr(c, "flush"):
                try:
                    c.flush()
                except Exception as e:
                    log.debug("lightstep flush: %s", e)
