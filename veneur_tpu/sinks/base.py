"""Sink interfaces.

reference sinks/sinks.go:32-48 MetricSink{Name, Start, Flush,
FlushOtherSamples} and :86-103 SpanSink{Name, Start, Ingest, Flush}. Tag
exclusion (SetExcludedTags) is wired from `tags_exclude` with the
`tag|sink1|sink2` per-sink syntax (reference server.go:1467-1510).
"""

from __future__ import annotations

from typing import Iterable, List

from veneur_tpu.samplers.intermetric import InterMetric


class MetricSink:
    name: str = "sink"

    # Every sink can take a columnar flusher.MetricFrame: the default
    # materializes (memoized on the frame, so N object-path sinks share
    # ONE InterMetric list); high-volume sinks override flush_frame to
    # consume frame.rows() directly and skip materialization.
    accepts_frames = True

    def start(self) -> None:
        pass

    def flush(self, metrics: List[InterMetric]) -> None:
        raise NotImplementedError

    def flush_frame(self, frame) -> None:
        self.flush(frame.intermetrics())

    def flush_other_samples(self, samples: Iterable) -> None:
        """DogStatsD events / service checks as SSF samples
        (reference sinks.go:44-47)."""

    def set_excluded_tags(self, tags: List[str]) -> None:
        self.excluded_tags = list(tags)

    def strip_excluded(self, tags: Iterable[str]) -> List[str]:
        excl = getattr(self, "excluded_tags", ())
        return [t for t in tags
                if not any(t == e or t.startswith(e + ":") for e in excl)]


class SpanSink:
    name: str = "span_sink"

    def start(self) -> None:
        pass

    def ingest(self, span) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass


def filter_acceptable(metrics: List[InterMetric], sink_name: str):
    """reference sinks/sinks.go:51 IsAcceptableMetric applied batch-wise."""
    return [m for m in metrics if m.is_acceptable_to(sink_name)]
