"""Sink interfaces.

reference sinks/sinks.go:32-48 MetricSink{Name, Start, Flush,
FlushOtherSamples} and :86-103 SpanSink{Name, Start, Ingest, Flush}. Tag
exclusion (SetExcludedTags) is wired from `tags_exclude` with the
`tag|sink1|sink2` per-sink syntax (reference server.go:1467-1510).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Iterable, List, Optional

from veneur_tpu.reliability.faults import FAULTS, SINK_FLUSH
from veneur_tpu.reliability.policy import CircuitOpenError
from veneur_tpu.samplers.intermetric import InterMetric

log = logging.getLogger("veneur_tpu.sinks")


class MetricSink:
    name: str = "sink"

    # Every sink can take a columnar flusher.MetricFrame: the default
    # materializes (memoized on the frame, so N object-path sinks share
    # ONE InterMetric list); high-volume sinks override flush_frame to
    # consume frame.rows() directly and skip materialization.
    accepts_frames = True

    def start(self) -> None:
        pass

    def flush(self, metrics: List[InterMetric]) -> None:
        raise NotImplementedError

    def flush_frame(self, frame) -> None:
        self.flush(frame.intermetrics())

    def flush_other_samples(self, samples: Iterable) -> None:
        """DogStatsD events / service checks as SSF samples
        (reference sinks.go:44-47)."""

    def set_excluded_tags(self, tags: List[str]) -> None:
        self.excluded_tags = list(tags)

    def strip_excluded(self, tags: Iterable[str]) -> List[str]:
        excl = getattr(self, "excluded_tags", ())
        return [t for t in tags
                if not any(t == e or t.startswith(e + ":") for e in excl)]


class SpanSink:
    name: str = "span_sink"

    def start(self) -> None:
        pass

    def ingest(self, span) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass


def filter_acceptable(metrics: List[InterMetric], sink_name: str):
    """reference sinks/sinks.go:51 IsAcceptableMetric applied batch-wise."""
    return [m for m in metrics if m.is_acceptable_to(sink_name)]


def dispatch_flush(sink, payload) -> None:
    """THE flush dispatch every fan-out path goes through: the `sink.flush`
    fault-injection point, then frame-vs-list routing. Keeping it here (not
    in server.py) means chaos tests hit the same seam any embedding does."""
    FAULTS.inject(SINK_FLUSH, name=sink.name)
    from veneur_tpu.server.flusher import MetricFrame
    if isinstance(payload, MetricFrame):
        sink.flush_frame(payload)
    else:
        sink.flush(payload)


class ResilientSink:
    """Mixin giving egress sinks (Datadog/SignalFx/Splunk/Kafka) a shared
    retry/breaker harness around their individual network calls.

    Unconfigured (the default), resilient_post() is a bare passthrough —
    today's single-attempt behavior, byte for byte. The server wires
    configure_resilience() from the sink_retry_* / circuit_* config keys;
    retrying HERE (per POST/produce) rather than around the whole flush
    avoids re-serializing and re-sending chunks that already landed.

    When a sink handles its own retries this way, the server fan-out does
    NOT wrap its flush in a second retry loop (resilience_configured is
    the signal) — otherwise errors would multiply attempts.
    """

    retry_policy = None
    breaker = None
    retries_total = 0        # drained by server self-telemetry per interval
    posts_skipped_open = 0   # refused by an open breaker

    def configure_resilience(self, policy, breaker=None) -> None:
        self.retry_policy = policy
        self.breaker = breaker
        self._resilience_lock = threading.Lock()
        self.retries_total = 0
        self.posts_skipped_open = 0

    @property
    def resilience_configured(self) -> bool:
        return self.retry_policy is not None or self.breaker is not None

    def reliability_counters(self):
        """(retries_total, posts_skipped_open) read under the harness
        lock — the server's telemetry-registry collectors call this so
        /metrics and the self-metric flush see consistent values."""
        lock = getattr(self, "_resilience_lock", None)
        if lock is None:   # configure_resilience never ran
            return (self.retries_total, self.posts_skipped_open)
        with lock:
            return (self.retries_total, self.posts_skipped_open)

    def resilient_post(self, fn: Callable, what: str = ""):
        """Run one network call under the sink's policy/breaker. Terminal
        failure re-raises — call sites keep their existing log-and-continue
        (or raise) semantics unchanged."""
        breaker = self.breaker
        if breaker is not None and not breaker.allow():
            with self._resilience_lock:
                self.posts_skipped_open += 1
            raise CircuitOpenError(
                f"{getattr(self, 'name', 'sink')} {what}: circuit open")
        policy = self.retry_policy
        if policy is None:
            try:
                result = fn()
            except Exception:
                if breaker is not None:
                    breaker.record_failure()
                raise
            # success must reset the breaker even without a retry policy:
            # otherwise sporadic (non-consecutive) failures accumulate to
            # a spurious trip, and a successful half-open probe would
            # leave _probe_in_flight set — wedging the breaker half-open
            if breaker is not None:
                breaker.record_success()
            return result
        name = getattr(self, "name", "sink")

        def on_retry(attempt, exc, delay):
            with self._resilience_lock:
                self.retries_total += 1
            log.warning("sink %s %s attempt %d failed: %s; retrying in "
                        "%.3fs", name, what, attempt + 1, exc, delay)

        try:
            result = policy.run(fn, on_retry=on_retry)
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        if breaker is not None:
            breaker.record_success()
        return result
